"""Feature-axis-tiled fused ensemble kernels (Pallas/TPU) — r11.

The untiled two-stage kernels (ops/fused_sae.py) keep a member's whole
[n_feats, d] dictionary — plus its gradient accumulator and normalized
copy, double-buffered — resident in VMEM, so exactly the paper's headline
sweep shapes at dict ratios 16–96 (reference standard_metrics.py:745,
big_sweep_experiments.py:543) never admitted a batch tile and silently
fell back to the ~1.8x-slower autodiff path (BENCH_VARIANTS.json). These
kernels port the flash-style (batch_tiles x feat_tiles) blocked-recompute
grid of ops/fused_big_sae.py to the vmapped ENSEMBLE step:

- **forward** — grid (members, batch_tiles, feat_tiles): each program
  row-normalizes its weight tile in registers and accumulates
  ``x̂[m, batch_tile] += relu(x·W_tᵀ + b_t) @ W_t``. Only x̂ [N, B, d]
  reaches HBM; the [B, n_feats] code matrix never exists anywhere.
- **residual** — one XLA elementwise pass forms r = x̂ − x [N, B, d].
- **backward** — grid (members, feat_tiles, batch_tiles): each program
  RECOMPUTES its code tile (the flash trade: ~2·B·Ft·d extra MXU flops
  instead of B·Ft·4-byte HBM round trips) and accumulates dW_t, db_t,
  activity and the member loss partials.
- **sentinel epilogue** — on each (member, feat-tile)'s LAST batch step
  the finished grad tile's squared norm folds into a per-member [N]
  reduction, so the PR-10 anomaly sentinel's grad-norm input comes out
  of the kernel for free instead of a second XLA ``optax.global_norm``
  pass over the [N, n, d] grads in HBM. The reported ``aux.grad_norm``
  is therefore the KERNEL-grad norm (pre normalization-VJP for the
  dictionary matrices) — equivalent for finiteness detection (the VJP
  is a row-local linear map with clipped denominators, so it neither
  creates nor destroys non-finites when params are finite), and the
  update-norm check still covers the full post-optimizer update.
  Under shard_map the per-shard partial grads make this nonlinear
  reduction wrong (‖Σ_shards g‖ ≠ √Σ_shards ‖g‖²), so sharded callers
  receive ``gnorm=None`` and fall back to the XLA norm after the psum.

Grid order matters on TPU: an output block accumulates in VMEM only
across CONSECUTIVE grid steps, so the per-batch x̂ lives in the
(batch, feat)-ordered forward grid and the per-feature grads in the
(feat, batch)-ordered backward grid (same rule as fused_big_sae.py).

Gradient semantics equal the untiled kernels' (same tile math, locked
against vmapped autodiff — including ratio-32 shapes — by
tests/test_fused_tiled.py). VMEM admission and the tiled-vs-untiled-vs-
autodiff path choice live in ops/roofline.py.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from sparse_coding_tpu.ops.fused_sae import (
    _DB,
    VMEM_BUDGET_BYTES,
    VMEM_LIMIT_BYTES,
    normalize_with_vjp,
    tpu_compiler_params,
    untied_bias_decay_terms,
)

Array = jax.Array

# tile candidates in preference order (first dividing + VMEM-fitting combo
# wins; batch tile scanned outermost). Real sweep shapes land on the
# 1024–4096 feature entries; the small entries serve the ft == n_feats
# equality case (Mosaic's lane rule below) so small-n buckets still ride
# the tiled program as a degenerate one-feature-tile grid.
TILED_BATCH_TILES: tuple = (512, 256, 128, 64)
TILED_FEAT_TILES: tuple = (4096, 2048, 1024, 512, 256, 128, 64, 32, 16, 8)


def _lane_legal(n_feats: int, ft: int) -> bool:
    # Mosaic lane rule for the [1, 1, ft] bias/db/activity blocks: the
    # last block dim must be a multiple of 128 or equal the whole array
    # dim (caught by the AOT lowering gates; interpret mode — the CPU
    # parity/fault drills — is exempt via lane_rule=False)
    return ft == n_feats or ft % 128 == 0


def _tiled_fwd_working_set(bt: int, ft: int, d: int,
                           batch_itemsize: int = 4,
                           compute_itemsize: int = 4,
                           n_mats: int = 1) -> int:
    """VMEM model for the forward kernel (same conventions as
    fused_sae._working_set: grid-varying in/out blocks ×_DB for Mosaic's
    double buffering, in-kernel intermediates ×1, sub-f32 cast copies
    counted)."""
    f32 = 4
    cast_copy = f32 if batch_itemsize < f32 else 0
    extra = 0
    if compute_itemsize < f32:
        extra = (ft * d * compute_itemsize * n_mats   # weight-tile casts
                 + bt * ft * compute_itemsize         # c cast
                 + (0 if batch_itemsize == compute_itemsize
                    else bt * d * compute_itemsize))  # xc
    blocks = (ft * d * f32 * n_mats      # weight tile(s) in
              + bt * d * batch_itemsize  # x tile (stream width)
              + bt * d * f32             # x̂ accumulator out
              + ft * f32 * 2)            # b (+ coef_mask)
    interm = (bt * ft * f32 * 2          # pre/c + decode partial
              + bt * d * cast_copy
              + ft * d * f32             # normalized weight tile
              + extra)
    return _DB * blocks + interm


def _tiled_bwd_working_set(bt: int, ft: int, d: int,
                           batch_itemsize: int = 4,
                           compute_itemsize: int = 4,
                           n_mats: int = 1) -> int:
    """VMEM model for the backward kernel — the larger of the pair (it
    carries the residual tile and the grad accumulators on top of the
    forward's set); admission checks both anyway."""
    f32 = 4
    cast_copy = f32 if batch_itemsize < f32 else 0
    extra = 0
    if compute_itemsize < f32:
        extra = (ft * d * compute_itemsize * n_mats
                 + bt * d * compute_itemsize          # rc
                 + bt * ft * compute_itemsize * 2     # c cast, dpre cast
                 + (0 if batch_itemsize == compute_itemsize
                    else bt * d * compute_itemsize))  # xc
    blocks = (ft * d * f32 * 2 * n_mats  # weight tiles in + grad accums out
              + bt * d * batch_itemsize  # x tile
              + bt * d * f32             # r tile
              + ft * f32 * 4             # b, db, act (+ coef_mask)
              + 4 * f32)                 # loss/gnorm vector
    interm = (bt * ft * f32 * 3          # pre/c, dpre, mask
              + bt * d * cast_copy
              + ft * d * f32             # normalized weight tile
              + extra)
    return _DB * blocks + interm


def tiled_tiles_fit(batch: int, bt: int, n_feats: int, ft: int, d: int,
                    batch_itemsize: int = 4, compute_itemsize: int = 4,
                    n_mats: int = 1, lane_rule: bool = True) -> bool:
    """Would this EXPLICIT (batch_tile, feat_tile) pair work? Divides both
    axes, respects Mosaic's lane rule on the feature tile (skipped for
    interpret-mode callers via lane_rule=False), and both kernels' working
    sets fit the VMEM budget."""
    return (batch % bt == 0 and n_feats % ft == 0
            and (not lane_rule or _lane_legal(n_feats, ft))
            and _tiled_fwd_working_set(bt, ft, d, batch_itemsize,
                                       compute_itemsize, n_mats)
            <= VMEM_BUDGET_BYTES
            and _tiled_bwd_working_set(bt, ft, d, batch_itemsize,
                                       compute_itemsize, n_mats)
            <= VMEM_BUDGET_BYTES)


def pick_tiled_tiles(batch: int, n_feats: int, d: int,
                     batch_itemsize: int = 4, compute_itemsize: int = 4,
                     n_mats: int = 1,
                     batch_tile: Optional[int] = None,
                     feat_tile: Optional[int] = None,
                     lane_rule: bool = True
                     ) -> Optional[tuple[int, int]]:
    """Largest admissible (batch_tile, feat_tile): batch tile scanned
    outermost (preference order TILED_BATCH_TILES × TILED_FEAT_TILES),
    each axis pinnable by an explicit tile (Ensemble fused_batch_tile /
    fused_feat_tile, tune.py's scans); None when nothing divides + fits."""
    bts = (batch_tile,) if batch_tile is not None else TILED_BATCH_TILES
    fts = (feat_tile,) if feat_tile is not None else TILED_FEAT_TILES
    for bt in bts:
        if batch % bt:
            continue
        for ft in fts:
            if n_feats % ft:
                continue
            if tiled_tiles_fit(batch, bt, n_feats, ft, d, batch_itemsize,
                               compute_itemsize, n_mats,
                               lane_rule=lane_rule):
                return bt, ft
    return None


# --- kernels -----------------------------------------------------------------


def _normalize_tile(mat):
    # same formula as the untiled kernels' in-scratch normalization
    # (fused_sae._kernel/_untied_kernel): rows live wholly inside a
    # [ftile, d] block, so the reduction is tile-local
    norms = jnp.sqrt(jnp.sum(mat * mat, axis=-1, keepdims=True))
    return mat / jnp.clip(norms, 1e-8)


def _fwd_kernel(x_ref, e_ref, *rest, tied: bool, masked: bool,
                compute_dtype):
    import jax.experimental.pallas as pl

    rest = list(rest)
    dec_ref = None if tied else rest.pop(0)
    b_ref = rest.pop(0)
    mask_ref = rest.pop(0) if masked else None
    (xhat_ref,) = rest

    ft = pl.program_id(2)
    x_in = x_ref[...]
    xb = x_in.astype(jnp.float32)
    xc = x_in if x_in.dtype == compute_dtype else xb.astype(compute_dtype)

    if tied:
        enc = _normalize_tile(e_ref[0]).astype(compute_dtype)
        dec = enc
    else:
        enc = e_ref[0].astype(compute_dtype)
        dec = _normalize_tile(dec_ref[0]).astype(compute_dtype)

    pre = (jnp.dot(xc, enc.T, preferred_element_type=jnp.float32)
           + b_ref[0, 0][None, :])
    c = jnp.maximum(pre, 0.0)
    if masked:
        c = c * mask_ref[0, 0][None, :]
    part = jnp.dot(c.astype(compute_dtype), dec,
                   preferred_element_type=jnp.float32)

    @pl.when(ft == 0)
    def _init():
        xhat_ref[0] = part

    @pl.when(ft > 0)
    def _acc():
        xhat_ref[0] += part


def _bwd_kernel(alpha_ref, x_ref, r_ref, e_ref, *rest, total_batch: int,
                d_act: int, n_bt: int, tied: bool, masked: bool,
                compute_dtype):
    import jax.experimental.pallas as pl

    rest = list(rest)
    dec_ref = None if tied else rest.pop(0)
    b_ref = rest.pop(0)
    mask_ref = rest.pop(0) if masked else None
    if tied:
        dw_ref, db_ref, act_ref, loss_ref = rest
        de_ref = dwn_ref = None
    else:
        de_ref, dwn_ref, db_ref, act_ref, loss_ref = rest
        dw_ref = None

    m = pl.program_id(0)
    ft_idx = pl.program_id(1)
    bt_idx = pl.program_id(2)

    x_in = x_ref[...]
    xb = x_in.astype(jnp.float32)
    xc = x_in if x_in.dtype == compute_dtype else xb.astype(compute_dtype)
    r = r_ref[0]  # [Bt, d] f32 (precomputed residual)
    rc = r.astype(compute_dtype)
    alpha = alpha_ref[m]
    b = b_ref[0, 0]

    if tied:
        enc = _normalize_tile(e_ref[0]).astype(compute_dtype)
        dec = enc
    else:
        enc = e_ref[0].astype(compute_dtype)
        dec = _normalize_tile(dec_ref[0]).astype(compute_dtype)

    # code-tile recomputation (the flash trade)
    pre = jnp.dot(xc, enc.T, preferred_element_type=jnp.float32) + b[None, :]
    c = jnp.maximum(pre, 0.0)
    mask = (pre > 0.0).astype(jnp.float32)
    if masked:
        cm = mask_ref[0, 0][None, :]
        c = c * cm
        mask = mask * cm

    coef = 2.0 / (total_batch * d_act)
    dpre = (coef * jnp.dot(rc, dec.T, preferred_element_type=jnp.float32)
            + alpha / total_batch) * mask
    dprec = dpre.astype(compute_dtype)
    cc = c.astype(compute_dtype)
    if tied:
        dmain = (jnp.dot(dprec.T, xc, preferred_element_type=jnp.float32)
                 + coef * jnp.dot(cc.T, rc,
                                  preferred_element_type=jnp.float32))
    else:
        de = jnp.dot(dprec.T, xc, preferred_element_type=jnp.float32)
        dwn = coef * jnp.dot(cc.T, rc, preferred_element_type=jnp.float32)
    db = jnp.sum(dpre, axis=0)
    activity = jnp.sum(mask, axis=0)
    zero = jnp.zeros((), jnp.float32)
    # mse comes from the residual tile and must count once per batch tile,
    # not once per feature tile
    mse_part = jnp.where(ft_idx == 0,
                         jnp.sum(r * r) / (total_batch * d_act), 0.0)
    part = jnp.stack([mse_part, alpha * jnp.sum(c) / total_batch,
                      jnp.sum(mask) / total_batch, zero])[None, None, :]

    @pl.when(bt_idx == 0)
    def _init():
        if tied:
            dw_ref[0] = dmain
        else:
            de_ref[0] = de
            dwn_ref[0] = dwn
        db_ref[0, 0] = db
        act_ref[0, 0] = activity

    @pl.when(bt_idx > 0)
    def _acc():
        if tied:
            dw_ref[0] += dmain
        else:
            de_ref[0] += de
            dwn_ref[0] += dwn
        db_ref[0, 0] += db
        act_ref[0, 0] += activity

    first = jnp.logical_and(ft_idx == 0, bt_idx == 0)

    @pl.when(first)
    def _loss_init():
        loss_ref[...] = part

    @pl.when(jnp.logical_not(first))
    def _loss_acc():
        loss_ref[...] += part

    # sentinel epilogue: fold this feature tile's FINISHED grads into the
    # member's grad squared norm on its last batch step — the PR-10
    # sentinel's norm reduction rides the kernel, no extra HBM pass
    @pl.when(bt_idx == n_bt - 1)
    def _gnorm():
        if tied:
            g = jnp.sum(dw_ref[0] * dw_ref[0])
        else:
            g = (jnp.sum(de_ref[0] * de_ref[0])
                 + jnp.sum(dwn_ref[0] * dwn_ref[0]))
        dbf = db_ref[0, 0]
        g = g + jnp.sum(dbf * dbf)
        loss_ref[...] += jnp.stack([zero, zero, zero, g])[None, None, :]


# --- pallas_call wrappers ----------------------------------------------------


def _fwd_call(encoder, decoder, bias3, mask3, batch, batch_tile, feat_tile,
              interpret, compute_dtype):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n_members, n_feats, d = encoder.shape
    local_batch = batch.shape[0]
    tied = decoder is None
    masked = mask3 is not None
    kernel = functools.partial(_fwd_kernel, tied=tied, masked=masked,
                               compute_dtype=jnp.dtype(compute_dtype))

    big = pl.BlockSpec((1, feat_tile, d), lambda m, b, f: (m, f, 0))
    vec = pl.BlockSpec((1, 1, feat_tile), lambda m, b, f: (m, 0, f))
    in_specs = [pl.BlockSpec((batch_tile, d), lambda m, b, f: (b, 0)),  # x
                big]                                                    # E
    operands = [batch, encoder]
    if not tied:
        in_specs.append(big)          # raw decoder
        operands.append(decoder)
    in_specs.append(vec)              # b
    operands.append(bias3)
    if masked:
        in_specs.append(vec)          # coef_mask
        operands.append(mask3)

    # members and batch tiles own disjoint x̂ blocks (parallel); the
    # feature axis accumulates into them and must stay sequential
    compiler_params = (None if interpret else tpu_compiler_params(
        dimension_semantics=("parallel", "parallel", "arbitrary"),
        vmem_limit_bytes=VMEM_LIMIT_BYTES))
    return pl.pallas_call(
        kernel,
        grid=(n_members, local_batch // batch_tile, n_feats // feat_tile),
        in_specs=in_specs,
        out_specs=pl.BlockSpec((1, batch_tile, d), lambda m, b, f: (m, b, 0)),
        out_shape=jax.ShapeDtypeStruct((n_members, local_batch, d),
                                       jnp.float32),
        interpret=interpret,
        compiler_params=compiler_params,
    )(*operands)


def _bwd_call(alphas, encoder, decoder, bias3, mask3, batch, resid,
              batch_tile, feat_tile, interpret, total_batch, compute_dtype):
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n_members, n_feats, d = encoder.shape
    local_batch = batch.shape[0]
    n_bt = local_batch // batch_tile
    tied = decoder is None
    masked = mask3 is not None
    kernel = functools.partial(
        _bwd_kernel, total_batch=total_batch, d_act=d, n_bt=n_bt,
        tied=tied, masked=masked, compute_dtype=jnp.dtype(compute_dtype))

    big = pl.BlockSpec((1, feat_tile, d), lambda m, f, b, *_: (m, f, 0))
    vec = pl.BlockSpec((1, 1, feat_tile), lambda m, f, b, *_: (m, 0, f))
    in_specs = [
        pl.BlockSpec((batch_tile, d), lambda m, f, b, *_: (b, 0)),   # x
        pl.BlockSpec((1, batch_tile, d), lambda m, f, b, *_: (m, b, 0)),  # r
        big,                                                         # E
    ]
    operands = [batch, resid, encoder]
    if not tied:
        in_specs.append(big)
        operands.append(decoder)
    in_specs.append(vec)
    operands.append(bias3)
    if masked:
        in_specs.append(vec)
        operands.append(mask3)

    n_big_out = 1 if tied else 2
    out_specs = ([big] * n_big_out
                 + [vec, vec,
                    pl.BlockSpec((1, 1, 4), lambda m, f, b, *_: (m, 0, 0))])
    out_shape = ([jax.ShapeDtypeStruct((n_members, n_feats, d), jnp.float32)]
                 * n_big_out
                 + [jax.ShapeDtypeStruct((n_members, 1, n_feats),
                                         jnp.float32)] * 2
                 + [jax.ShapeDtypeStruct((n_members, 1, 4), jnp.float32)])

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n_members, n_feats // feat_tile, n_bt),
        in_specs=in_specs,
        out_specs=out_specs,
    )
    # the loss/gnorm block is shared across the feature axis (every tile
    # accumulates into it), so only the member axis may be parallel
    compiler_params = (None if interpret else tpu_compiler_params(
        dimension_semantics=("parallel", "arbitrary", "arbitrary"),
        vmem_limit_bytes=VMEM_LIMIT_BYTES))
    return pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        out_shape=out_shape,
        interpret=interpret,
        compiler_params=compiler_params,
    )(alphas.astype(jnp.float32), *operands)


@functools.partial(jax.jit,
                   static_argnames=("batch_tile", "feat_tile", "interpret",
                                    "total_batch", "compute_dtype"))
def tiled_tied_sae_grads(encoder: Array, bias: Array, alphas: Array,
                         batch: Array, batch_tile: int, feat_tile: int,
                         interpret: bool = False,
                         total_batch: Optional[int] = None,
                         compute_dtype: str = "float32",
                         coef_mask: Optional[Array] = None):
    """All-member tied-SAE losses and RAW kernel gradients via the tiled
    forward/backward pair. Returns (losses {mse, l1, l0}, dW [N, n, d] wrt
    the row-normalized W — chain through normalize_with_vjp for dE,
    db [N, n], activity [N, n], grad_sq [N] — the sentinel's per-member
    kernel-grad squared norm, accumulated in the backward epilogue).
    total_batch: global batch under shard_map (see fused_tied_sae_grads)."""
    n_members, n_feats, d = encoder.shape
    if total_batch is None:
        total_batch = batch.shape[0]
    assert batch.shape[0] % batch_tile == 0
    assert n_feats % feat_tile == 0
    bias3 = bias.reshape(n_members, 1, n_feats)
    mask3 = (None if coef_mask is None
             else coef_mask.astype(jnp.float32).reshape(n_members, 1, n_feats))
    xhat = _fwd_call(encoder, None, bias3, mask3, batch, batch_tile,
                     feat_tile, interpret, compute_dtype)
    resid = xhat - batch.astype(jnp.float32)[None]
    dw, db, act, loss4 = _bwd_call(
        alphas, encoder, None, bias3, mask3, batch, resid, batch_tile,
        feat_tile, interpret, total_batch, compute_dtype)
    loss4 = loss4.reshape(n_members, 4)
    losses = {"mse": loss4[:, 0], "l1": loss4[:, 1], "l0": loss4[:, 2]}
    return (losses, dw, db.reshape(n_members, n_feats),
            act.reshape(n_members, n_feats), loss4[:, 3])


@functools.partial(jax.jit,
                   static_argnames=("batch_tile", "feat_tile", "interpret",
                                    "total_batch", "compute_dtype"))
def tiled_untied_sae_grads(encoder: Array, decoder: Array, bias: Array,
                           alphas: Array, batch: Array, batch_tile: int,
                           feat_tile: int, interpret: bool = False,
                           total_batch: Optional[int] = None,
                           compute_dtype: str = "float32"):
    """Untied (FunctionalSAE) tiled grads: (losses, dE raw, dWn wrt the
    normalized decoder, db, activity, grad_sq [N]). Bias-decay terms are
    the caller's (untied_bias_decay_terms), exactly as in the untiled
    path."""
    n_members, n_feats, d = encoder.shape
    if total_batch is None:
        total_batch = batch.shape[0]
    assert batch.shape[0] % batch_tile == 0
    assert n_feats % feat_tile == 0
    bias3 = bias.reshape(n_members, 1, n_feats)
    xhat = _fwd_call(encoder, decoder, bias3, None, batch, batch_tile,
                     feat_tile, interpret, compute_dtype)
    resid = xhat - batch.astype(jnp.float32)[None]
    de, dwn, db, act, loss4 = _bwd_call(
        alphas, encoder, decoder, bias3, None, batch, resid, batch_tile,
        feat_tile, interpret, total_batch, compute_dtype)
    loss4 = loss4.reshape(n_members, 4)
    losses = {"mse": loss4[:, 0], "l1": loss4[:, 1], "l0": loss4[:, 2]}
    return (losses, de, dwn, db.reshape(n_members, n_feats),
            act.reshape(n_members, n_feats), loss4[:, 3])


# --- producer-level wrappers (ensemble entry points) -------------------------


def prepare_tiled_batch(batch: Array, n_feats: int, d: int,
                        batch_tile: Optional[int], feat_tile: Optional[int],
                        compute_dtype: str,
                        n_mats: int = 1,
                        lane_rule: bool = True) -> tuple[Array, int, int]:
    """Tiled twin of fused_sae.prepare_kernel_batch: same dtype contract
    (bf16 streams pass half-width, everything else casts to f32), then the
    (batch, feature) tile pair resolves through pick_tiled_tiles — the
    SAME admission rule ops/roofline.py applies, so resolution and the
    kernels can never disagree. lane_rule=False (interpret-mode callers)
    admits feature tiles Mosaic's lane rule would reject on real TPU."""
    if batch.dtype != jnp.bfloat16:
        batch = batch.astype(jnp.float32)
    pair = pick_tiled_tiles(
        batch.shape[0], n_feats, d,
        batch_itemsize=batch.dtype.itemsize,
        compute_itemsize=jnp.dtype(compute_dtype).itemsize,
        n_mats=n_mats, batch_tile=batch_tile, feat_tile=feat_tile,
        lane_rule=lane_rule)
    if pair is None:
        raise ValueError(
            f"no VMEM-fitting (batch, feature) tile pair for shapes "
            f"n={n_feats} d={d} batch={batch.shape[0]} "
            f"(batch_tile={batch_tile}, feat_tile={feat_tile}); "
            f"use the autodiff path")
    return batch, pair[0], pair[1]


def fused_tied_sae_tiled_loss_and_grads(
        params_stacked: dict, alphas: Array, batch: Array,
        batch_tile: Optional[int] = None, feat_tile: Optional[int] = None,
        interpret: bool = False, total_batch: Optional[int] = None,
        compute_dtype: str = "float32", psum_axis: Optional[str] = None,
        coef_mask: Optional[Array] = None):
    """Tiled-path producer for tied (and masked-tied) buckets: same
    contract as fused_tied_sae_loss_and_grads plus a 4th return — the
    per-member kernel-grad norm [N] from the backward epilogue (None
    under shard_map, where the per-shard partials make the reduction
    wrong; the sharded sentinel falls back to the XLA norm)."""
    e = params_stacked["encoder"]
    batch, bt, ft = prepare_tiled_batch(
        batch, e.shape[1], e.shape[2], batch_tile, feat_tile, compute_dtype,
        lane_rule=not interpret)
    losses, dw, db, activity, grad_sq = tiled_tied_sae_grads(
        e, params_stacked["encoder_bias"], alphas, batch, batch_tile=bt,
        feat_tile=ft, interpret=interpret, total_batch=total_batch,
        compute_dtype=compute_dtype, coef_mask=coef_mask)
    if psum_axis is not None:
        losses, dw, db, activity = jax.lax.psum(
            (losses, dw, db, activity), psum_axis)
        gnorm = None
    else:
        gnorm = jnp.sqrt(grad_sq)
    grads = {"encoder": normalize_with_vjp(e, dw), "encoder_bias": db}
    return losses, grads, activity, gnorm


def fused_untied_sae_tiled_loss_and_grads(
        params_stacked: dict, alphas: Array, bias_decays: Array,
        batch: Array, batch_tile: Optional[int] = None,
        feat_tile: Optional[int] = None, interpret: bool = False,
        total_batch: Optional[int] = None, compute_dtype: str = "float32",
        psum_axis: Optional[str] = None):
    """Tiled-path producer for untied FunctionalSAE buckets (contract of
    fused_untied_sae_loss_and_grads + the kernel-grad norm; the
    batch-independent bias-decay terms are added AFTER the psum, exactly
    once per member)."""
    e = params_stacked["encoder"]
    dec = params_stacked["decoder"]
    batch, bt, ft = prepare_tiled_batch(
        batch, e.shape[1], e.shape[2], batch_tile, feat_tile, compute_dtype,
        n_mats=2, lane_rule=not interpret)
    losses, de, dwn, db, activity, grad_sq = tiled_untied_sae_grads(
        e, dec, params_stacked["encoder_bias"], alphas, batch,
        batch_tile=bt, feat_tile=ft, interpret=interpret,
        total_batch=total_batch, compute_dtype=compute_dtype)
    if psum_axis is not None:
        losses, de, dwn, db, activity = jax.lax.psum(
            (losses, de, dwn, db, activity), psum_axis)
        gnorm = None
    else:
        gnorm = jnp.sqrt(grad_sq)
    bias = params_stacked["encoder_bias"]
    decay_loss, db = untied_bias_decay_terms(bias, bias_decays, db)
    losses["bias_decay"] = decay_loss
    grads = {"encoder": de, "encoder_bias": db,
             "decoder": normalize_with_vjp(dec, dwn)}
    return losses, grads, activity, gnorm
