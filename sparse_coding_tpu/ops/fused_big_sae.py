"""Flash-style fused train-step kernels for the GIANT single SAE (Pallas/TPU).

The big-SAE step (train/big_sae.py::_sae_loss; reference:
experiments/huge_batch_size.py:50-98) is HBM-bound under XLA autodiff for
exactly one reason: the [batch, n_feats] code matrix. At the reference's DDP
scale (batch 16384, n_feats 16384) that is a ~1 GB array which XLA
materializes in the forward, reads back for the ReLU mask in the backward,
plus the same again for the L1 subgradient — several full HBM round trips
per step. These kernels never materialize it:

- **forward kernel**: grid (batch_tiles, feat_tiles); each program computes
  its code tile in VMEM and accumulates `x̂[batch_tile] += c_tile @ Wn_tile`.
  Only x̂ [B, d] ever reaches HBM.
- **backward kernel**: grid (feat_tiles, batch_tiles); each program
  RECOMPUTES its code tile (the flash-attention trade: ~2·B·n·d extra MXU
  flops to skip ~4 HBM round trips of B·n·4 bytes) and accumulates all
  parameter grads + the training metrics:
      pre = xc Eₜ + tₜ,  c = relu(pre)
      dc  = (2/(B·d))·r Wnₜᵀ + α/B          (L1: c ≥ 0 so ∂|c| = mask)
      dpre = dc ⊙ [pre > 0]
      dEₜ  += xcᵀ dpre        dWnₜ += (2/(B·d))·cᵀ r
      dtₜ  += Σ_b dpre        dctr_enc += −Σ_b dpre Eₜᵀ
      c_totalsₜ += Σ_b c      l1 += Σ c      l0 += Σ mask
  Grid order matters on TPU: an output block must be revisited on
  CONSECUTIVE grid steps to accumulate in VMEM, so per-feature outputs live
  in the (feat, batch)-ordered backward grid and the per-batch x̂ lives in
  the (batch, feat)-ordered forward grid.

Everything cheap or shape-small stays outside in XLA: centering subtract,
r = x̂ (+ctr if tied) − x, per-example MSEs (worst-example tracking), the
dict-normalization VJP chain (ops/fused_sae.normalize_with_vjp), and the
tied decode-centering gradient Σ (2/(B·d))·r.

Since r11 this (batch_tiles × feat_tiles) blocked-recompute grid is no
longer big-SAE-only: ops/fused_sae_tiled.py ports it to the vmapped
ENSEMBLE kernels, so the old "ensemble kernels need the full [n, d]
working set per member" rule is gone — canonical ratio-16/96 sweep
shapes ride a tiled fused path there, with admission decided by the
roofline model in ops/roofline.py (which also covers this pair's
shapes conceptually; pick_big_sae_tiles below stays this file's
concrete VMEM gate).

Gradient semantics match jax.grad of train/big_sae.py::_sae_loss exactly
(locked by tests/test_fused_big_sae.py).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp

from sparse_coding_tpu.ops.fused_sae import (
    _DB,
    VMEM_BUDGET_BYTES,
    VMEM_LIMIT_BYTES,
    normalize_with_vjp,
    tpu_compiler_params,
)

Array = jax.Array


def _bwd_working_set(bt: int, ft: int, d: int,
                     compute_itemsize: int = 4) -> int:
    f32 = 4
    # compute_dtype=bf16 materializes bf16 copies of the dot operands:
    # xc, rc, E, Wn, the c cast, and dprec
    extra = (0 if compute_itemsize >= f32 else
             (bt * d * 2 + d * ft + ft * d + bt * ft * 2) * compute_itemsize)
    # in/out blocks ×_DB (Mosaic double-buffering, see fused_sae budget
    # comment); in-kernel intermediates single
    blocks = (
        d * ft * f32 * 2      # E tile + dE accumulator
        + ft * d * f32 * 2    # Wn tile + dWn accumulator
        + bt * d * f32 * 2    # xc, r input tiles
        + ft * f32 * 4        # t, dt, c_totals, act
        + d * f32             # dctr
    )
    interm = (
        bt * d * f32          # dpre@Eᵀ
        + bt * ft * f32 * 3   # pre/c, r@Wnᵀ/dpre, mask
        + extra
    )
    return _DB * blocks + interm


def _fwd_working_set(bt: int, ft: int, d: int,
                     compute_itemsize: int = 4) -> int:
    f32 = 4
    extra = (0 if compute_itemsize >= f32 else
             (bt * d + d * ft + ft * d + bt * ft) * compute_itemsize)
    blocks = (
        d * ft * f32          # E tile
        + ft * d * f32        # Wn tile
        + bt * d * f32 * 2    # xc tile + x̂ accumulator
        + ft * f32            # t
    )
    interm = bt * ft * f32 * 2 + extra  # pre/c
    return _DB * blocks + interm


def pick_big_sae_tiles(batch: int, n_feats: int, d: int,
                       compute_itemsize: int = 4
                       ) -> Optional[tuple[int, int]]:
    """Largest (batch_tile, feat_tile) whose BACKWARD working set (the
    bigger of the two kernels) fits the VMEM budget and which divide the
    problem; None if nothing fits (caller uses the autodiff path).
    `compute_itemsize` is 2 for compute_dtype=bfloat16 (in-VMEM operand
    cast copies are counted). Lane-dim sanity: d and the feat tile should
    be multiples of 128 for clean Mosaic tiling — non-multiples fall
    back."""
    if d % 128 != 0:
        return None
    for bt in (512, 256, 128, 64):
        if batch % bt:
            continue
        for ft in (1024, 512, 256, 128):
            if n_feats % ft:
                continue
            if (_bwd_working_set(bt, ft, d, compute_itemsize)
                    <= VMEM_BUDGET_BYTES
                    and _fwd_working_set(bt, ft, d, compute_itemsize)
                    <= VMEM_BUDGET_BYTES):
                return bt, ft
    return None


def _fwd_kernel(xc_ref, e_ref, w_ref, t_ref, xhat_ref, *, compute_dtype):
    import jax.experimental.pallas as pl

    ft = pl.program_id(1)
    # compute_dtype=bf16: dot operands cast to bf16 in VMEM for the MXU's
    # native fast path, f32 accumulation (same contract as fused_sae._kernel)
    xc = xc_ref[...].astype(compute_dtype)  # [Bt, d]
    pre = (jnp.dot(xc, e_ref[...].astype(compute_dtype),
                   preferred_element_type=jnp.float32)
           + t_ref[0][None, :])           # [Bt, Ft]
    c = jnp.maximum(pre, 0.0)
    part = jnp.dot(c.astype(compute_dtype), w_ref[...].astype(compute_dtype),
                   preferred_element_type=jnp.float32)

    @pl.when(ft == 0)
    def _init():
        xhat_ref[...] = part

    @pl.when(ft > 0)
    def _acc():
        xhat_ref[...] += part


def _bwd_kernel(alpha_ref, xc_ref, r_ref, e_ref, w_ref, t_ref,
                de_ref, dw_ref, dt_ref, dctr_ref, act_ref, scal_ref,
                *, total_batch: int, d_act: int, compute_dtype):
    import jax.experimental.pallas as pl

    bt_idx = pl.program_id(1)
    xc = xc_ref[...].astype(compute_dtype)   # [Bt, d]
    r = r_ref[...]                           # [Bt, d] (f32: metrics source)
    rc = r.astype(compute_dtype)
    e = e_ref[...].astype(compute_dtype)     # [d, Ft]
    w = w_ref[...].astype(compute_dtype)     # [Ft, d]
    alpha = alpha_ref[0]

    pre = (jnp.dot(xc, e, preferred_element_type=jnp.float32)
           + t_ref[0][None, :])
    c = jnp.maximum(pre, 0.0)
    mask = (pre > 0.0).astype(jnp.float32)
    coef = 2.0 / (total_batch * d_act)
    dc = (coef * jnp.dot(rc, w.T, preferred_element_type=jnp.float32)
          + alpha / total_batch)
    dpre = dc * mask
    dprec = dpre.astype(compute_dtype)
    de = jnp.dot(xc.T, dprec, preferred_element_type=jnp.float32)
    dw = coef * jnp.dot(c.astype(compute_dtype).T, rc,
                        preferred_element_type=jnp.float32)
    dt = jnp.sum(dpre, axis=0)
    dctr = -jnp.sum(jnp.dot(dprec, e.T, preferred_element_type=jnp.float32),
                    axis=0)
    activity = jnp.sum(c, axis=0)
    scal = jnp.stack([jnp.sum(c), jnp.sum(mask)])[None, :]  # l1, l0 sums

    @pl.when(bt_idx == 0)
    def _init():
        de_ref[...] = de
        dw_ref[...] = dw
        dt_ref[0] = dt
        act_ref[0] = activity

    @pl.when(bt_idx > 0)
    def _acc():
        de_ref[...] += de
        dw_ref[...] += dw
        dt_ref[0] += dt
        act_ref[0] += activity

    first = jnp.logical_and(bt_idx == 0, pl.program_id(0) == 0)

    @pl.when(first)
    def _init_global():
        dctr_ref[0] = dctr
        scal_ref[...] = scal

    @pl.when(jnp.logical_not(first))
    def _acc_global():
        dctr_ref[0] += dctr
        scal_ref[...] += scal


@functools.partial(jax.jit, static_argnames=("batch_tile", "feat_tile",
                                             "interpret", "compute_dtype"))
def big_sae_forward(params: dict, xc: Array, batch_tile: int, feat_tile: int,
                    interpret: bool = False,
                    compute_dtype: str = "float32") -> Array:
    """x̂ = relu(xc E + t) @ Wn without materializing the codes. `params`
    holds raw big-SAE params (dict/encoder/threshold); xc is pre-centered."""
    import jax.experimental.pallas as pl

    b, d = xc.shape
    n = params["dict"].shape[0]
    wn = params["dict"] / jnp.linalg.norm(params["dict"], axis=-1,
                                          keepdims=True)
    from jax.experimental.pallas import tpu as pltpu

    kernel = functools.partial(_fwd_kernel,
                               compute_dtype=jnp.dtype(compute_dtype))
    # batch axis is parallel (disjoint x̂ blocks); feat axis accumulates
    # into them sequentially. vmem_limit_bytes: see fused_sae budget comment.
    compiler_params = (None if interpret else tpu_compiler_params(
        dimension_semantics=("parallel", "arbitrary"),
        vmem_limit_bytes=VMEM_LIMIT_BYTES))
    return pl.pallas_call(
        kernel,
        grid=(b // batch_tile, n // feat_tile),
        compiler_params=compiler_params,
        in_specs=[
            pl.BlockSpec((batch_tile, d), lambda bt, ft: (bt, 0)),   # xc
            pl.BlockSpec((d, feat_tile), lambda bt, ft: (0, ft)),    # E
            pl.BlockSpec((feat_tile, d), lambda bt, ft: (ft, 0)),    # Wn
            pl.BlockSpec((1, feat_tile), lambda bt, ft: (0, ft)),    # t
        ],
        out_specs=pl.BlockSpec((batch_tile, d), lambda bt, ft: (bt, 0)),
        out_shape=jax.ShapeDtypeStruct((b, d), jnp.float32),
        interpret=interpret,
    )(xc, params["encoder"], wn, params["threshold"].reshape(1, n))


@functools.partial(jax.jit, static_argnames=("batch_tile", "feat_tile",
                                             "interpret", "total_batch",
                                             "compute_dtype"))
def big_sae_backward(params: dict, alpha: Array, xc: Array, r: Array,
                     batch_tile: int, feat_tile: int,
                     interpret: bool = False,
                     total_batch: Optional[int] = None,
                     compute_dtype: str = "float32"):
    """All parameter grads (wrt raw E/t/normalized Wn/encode-side ctr) plus
    c_totals and the l1/l0 sums, one pass, codes recomputed per tile.
    total_batch: global batch for loss normalization (≠ local under
    shard_map, same convention as ops/fused_sae.fused_tied_sae_grads)."""
    import jax.experimental.pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    b, d = xc.shape
    n = params["dict"].shape[0]
    if total_batch is None:
        total_batch = b
    wn = params["dict"] / jnp.linalg.norm(params["dict"], axis=-1,
                                          keepdims=True)
    kernel = functools.partial(_bwd_kernel, total_batch=total_batch,
                               d_act=d,
                               compute_dtype=jnp.dtype(compute_dtype))
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,
        grid=(n // feat_tile, b // batch_tile),
        in_specs=[
            pl.BlockSpec((batch_tile, d), lambda ft, bt, *_: (bt, 0)),  # xc
            pl.BlockSpec((batch_tile, d), lambda ft, bt, *_: (bt, 0)),  # r
            pl.BlockSpec((d, feat_tile), lambda ft, bt, *_: (0, ft)),   # E
            pl.BlockSpec((feat_tile, d), lambda ft, bt, *_: (ft, 0)),   # Wn
            pl.BlockSpec((1, feat_tile), lambda ft, bt, *_: (0, ft)),   # t
        ],
        out_specs=[
            pl.BlockSpec((d, feat_tile), lambda ft, bt, *_: (0, ft)),   # dE
            pl.BlockSpec((feat_tile, d), lambda ft, bt, *_: (ft, 0)),   # dWn
            pl.BlockSpec((1, feat_tile), lambda ft, bt, *_: (0, ft)),   # dt
            pl.BlockSpec((1, d), lambda ft, bt, *_: (0, 0)),            # dctr
            pl.BlockSpec((1, feat_tile), lambda ft, bt, *_: (0, ft)),   # act
            pl.BlockSpec((1, 2), lambda ft, bt, *_: (0, 0)),            # l1/l0
        ],
    )
    # no dimension_semantics here: dctr/scal blocks are shared across the
    # feat axis (every program accumulates into them), so neither grid axis
    # may be declared parallel
    compiler_params = (None if interpret else tpu_compiler_params(
        vmem_limit_bytes=VMEM_LIMIT_BYTES))
    de, dwn, dt, dctr_enc, c_totals, scal = pl.pallas_call(
        kernel,
        grid_spec=grid_spec,
        compiler_params=compiler_params,
        out_shape=[
            jax.ShapeDtypeStruct((d, n), jnp.float32),
            jax.ShapeDtypeStruct((n, d), jnp.float32),
            jax.ShapeDtypeStruct((1, n), jnp.float32),
            jax.ShapeDtypeStruct((1, d), jnp.float32),
            jax.ShapeDtypeStruct((1, n), jnp.float32),
            jax.ShapeDtypeStruct((1, 2), jnp.float32),
        ],
        interpret=interpret,
    )(alpha.reshape(1), xc, r, params["encoder"], wn,
      params["threshold"].reshape(1, n))
    return (de, dwn, dt.reshape(n), dctr_enc.reshape(d),
            c_totals.reshape(n), scal.reshape(2))


def fused_big_sae_loss_and_grads(params: dict, batch: Array, l1_alpha: Array,
                                 tied: bool,
                                 batch_tile: Optional[int] = None,
                                 feat_tile: Optional[int] = None,
                                 interpret: bool = False,
                                 total_batch: Optional[int] = None,
                                 compute_dtype: str = "float32"):
    """Drop-in replacement for value_and_grad(_sae_loss) in the big-SAE step
    (train/big_sae.py): returns (loss, aux, grads) where aux is the dict
    {"mse", "sparsity", "c_totals_delta", "mse_losses", "l0_mean"} and
    grads is wrt the RAW param tree {dict, encoder, threshold, centering}."""
    b, d = batch.shape
    n = params["dict"].shape[0]
    if batch_tile is None or feat_tile is None:
        tiles = pick_big_sae_tiles(
            b, n, d, compute_itemsize=jnp.dtype(compute_dtype).itemsize)
        if tiles is None:
            raise ValueError(
                f"no VMEM-fitting (batch, feature) tiles for batch={b} "
                f"n_feats={n} d={d}; use the autodiff path")
        batch_tile, feat_tile = tiles
    if total_batch is None:
        total_batch = b

    batch = batch.astype(jnp.float32)
    xc = batch - params["centering"]
    x_hat = big_sae_forward(params, xc, batch_tile, feat_tile,
                            interpret=interpret, compute_dtype=compute_dtype)
    if tied:
        x_hat = x_hat + params["centering"]
    resid = x_hat - batch  # r in the kernel math
    mse_losses = jnp.mean(jnp.square(resid), axis=-1)  # per example
    mse = jnp.sum(jnp.square(resid)) / (total_batch * d)

    de, dwn, dt, dctr_enc, c_totals, scal = big_sae_backward(
        params, jnp.asarray(l1_alpha, jnp.float32), xc, resid,
        batch_tile, feat_tile, interpret=interpret, total_batch=total_batch,
        compute_dtype=compute_dtype)
    l1_sum, l0_sum = scal[0], scal[1]
    sparsity = jnp.asarray(l1_alpha, jnp.float32) * l1_sum / total_batch
    loss = mse + sparsity

    coef = 2.0 / (total_batch * d)
    dctr = dctr_enc + (coef * jnp.sum(resid, axis=0) if tied else 0.0)
    grads = {
        "dict": normalize_with_vjp(params["dict"], dwn),
        "encoder": de,
        "threshold": dt,
        "centering": dctr,
    }
    aux = {"mse": mse, "sparsity": sparsity, "c_totals_delta": c_totals,
           "mse_losses": mse_losses, "l0_mean": l0_sum / total_batch}
    return loss, aux, grads
