"""Roofline-driven kernel-path admission for the ensemble engine (r11).

``Ensemble._resolve_step`` used to make a BINARY choice: if the untiled
fused kernels' VMEM working set admitted a batch tile, ride them,
otherwise silently drop to XLA autodiff — which is exactly what happened
at the paper's canonical dict ratios 16–96 (the untiled kernels keep a
whole [n_feats, d] matrix resident per member). This module replaces
that with an explicit per-step accounting of **HBM bytes moved** and
**MXU flops executed** for every candidate kernel path, plus the VMEM
admission rule for each, and picks the ``(path, batch_tile, feat_tile)``
with the lowest modeled step time.

The model is for RANKING admissible paths, not predicting wall clock:

- ``est_s = max(hbm_bytes / HBM_BYTES_PER_S,
                mxu_flops / (MXU_PEAK_FLOPS · efficiency))`` — the
  classic roofline, with a measured efficiency for the Pallas kernels
  (0.61 MFU on-chip at the bench shape, BENCH_r02/BENCH_SUITE_TPU) and
  a calibrated discount for XLA autodiff (the fused/autodiff throughput
  ratio measured 1.5–1.8x at compute-bound shapes, BENCH_VARIANTS.json
  r4: 170k vs 112k acts/s).
- Chip constants default to v5e (the tunnel-attached generation);
  absolute seconds are wrong on other chips but every RANKING the
  engine needs is bandwidth/peak-ratio-stable across generations.
- Ties (common: same-flops paths at compute-bound shapes) break by the
  fixed preference order ``train_step > train_step_tiled > two_stage >
  two_stage_tiled`` — whole-step beats two-stage (the r4 on-chip A/B
  measured ~9%, consistent with its smaller byte count), untiled beats
  tiled (no recompute flops, no weight re-streaming).
- Autodiff is never RANKED against fused candidates — at every measured
  shape a fitting fused kernel won — it is the fallback when no fused
  tile admits (e.g. a batch size no candidate tile divides), and the
  resolution is now a counted, reported event
  (``ensemble.path_resolved`` — obs.report "kernel paths" section)
  instead of an invisible flip.

Per-step byte accounting (per member; N members; P = n·d·4 param bytes,
Pm with the moments itemsize, X = B·d·stream bytes, X4 = B·d·4,
C = B·n·4 the code matrix):

| path             | HBM bytes                                | flops    |
|------------------|------------------------------------------|----------|
| autodiff         | X4 + 4·C + 2·P·mats + adam + sentinel    | 12·B·n·d |
| two_stage        | X + 2·P·mats + adam + sentinel           | 10·B·n·d |
| train_step tied  | X + 2·(P+2·Pm) + 2·P (delta sentinel)    | 10·B·n·d |
| train_step untied| X + 4·P + epilogue                       | 10·B·n·d |
| two_stage_tiled  | fwd+resid+bwd streams + adam + ½sentinel | 12·B·n·d |
| train_step_tiled | fwd+resid+bwd streams + epilogue         | 12·B·n·d |

where ``adam = mats·(3·P + 4·Pm)`` (XLA optimizer pass), ``sentinel =
2·P·mats`` (the XLA grad+update global-norm passes the PR-10 sentinel
costs on paths that don't fold norms into a kernel epilogue — the tiled
kernels and the whole-step epilogues fold them, see
ops/fused_sae_tiled.py), ``epilogue = mats·(3·P + 4·Pm)`` (the fused
Adam/VJP kernel pass), and the tiled streams are
``(B/bt)·P·mats + X + X4`` (forward: weights re-streamed per batch
tile), ``2·X4 + X`` (residual formation), and
``(n/ft)·(X + X4) + 2·P·mats`` (backward: x and r re-streamed per
feature tile). The 12-vs-10 flops gap is the flash recompute trade.

Sharded (r15): the per-device step is modeled at the per-device batch
slice with the same table — ICI psum traffic is common to every fused
path and drops out of the ranking — except the tied ``train_step``,
which on a mesh is the grads-kernel + Adam/VJP-epilogue FACTORING
(``ensemble.make_fullfused_step_sharded``; the one-kernel pass cannot
shard because the data-axis psum must run between grads and Adam), so
its sharded cost/admission follow the untied epilogue form.

Unit-pinned by tests/test_roofline.py; the admission tile pickers are
the SAME functions the kernel wrappers call, so a chosen plan can never
disagree with the kernel's own admission.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

from sparse_coding_tpu.ops.fused_sae import (
    pick_batch_tile,
    pick_epilogue_tile,
    pick_tied_epilogue_tile,
    pick_train_step_tile,
    tile_fits,
    train_tile_fits,
)
from sparse_coding_tpu.ops.fused_sae_tiled import pick_tiled_tiles

# v5e spec-sheet constants (see the module docstring: ranking, not
# wall-clock) and the measured efficiency calibrations
HBM_BYTES_PER_S = 819e9
MXU_PEAK_FLOPS = 197e12
KERNEL_MXU_EFF = 0.61   # BENCH_r02 on-chip MFU at the bench shape
AUTODIFF_MXU_EFF = 0.35  # fused/autodiff ≈ 1.5–1.8x (BENCH_VARIANTS r4)

# every kernel path _resolve_step can land on; the parity-coverage lint
# (tests/test_roofline.py) asserts each has a named parity test
KERNEL_PATHS = ("train_step", "train_step_tiled", "two_stage",
                "two_stage_tiled")
_PREFERENCE = {p: i for i, p in enumerate(KERNEL_PATHS)}

# which paths exist per bucket family / placement. masked_tied: the
# coef_mask operand rides the two-stage grads kernels only. sharded
# (ISSUE 15): ALL paths — the whole-step variants shard by factoring
# the step as grads kernel → psum("data") → fused Adam/VJP epilogue
# kernel (ensemble.make_fullfused_step_sharded), so the data-axis psum
# runs exactly between the two kernels; only the tied ONE-kernel train
# step (fused_tied_sae_train_step) is single-device — under sharding
# the tied family rides the epilogue factoring instead.
FAMILY_PATHS = {
    "tied": KERNEL_PATHS,
    "untied": KERNEL_PATHS,
    "masked_tied": ("two_stage", "two_stage_tiled"),
}
SHARDED_PATHS = KERNEL_PATHS


@dataclasses.dataclass(frozen=True)
class KernelPlan:
    """One resolved admission decision: which program the next step runs
    and why. ``path`` is a KERNEL_PATHS entry, or None = autodiff.
    bytes/flops/est_s are the ranking model's numbers (whole step, all
    members)."""

    path: Optional[str]
    batch_tile: Optional[int] = None
    feat_tile: Optional[int] = None
    hbm_bytes: float = 0.0
    mxu_flops: float = 0.0
    est_s: float = 0.0
    reason: str = ""


def _est_s(hbm_bytes: float, mxu_flops: float, eff: float) -> float:
    return max(hbm_bytes / HBM_BYTES_PER_S,
               mxu_flops / (MXU_PEAK_FLOPS * eff))


def model_flops_per_activation(n_members: int, n_feats: int, d: int) -> float:
    """~12·n·d flops per activation per member: encode + decode matmuls
    forward (2·n·d each), ~2x for backward — the flops the MODEL requires,
    independent of which kernel path executes them (the tiled flash paths
    EXECUTE 12·B·n·d via recompute, the fused whole-step paths 10·B·n·d;
    see ``path_cost``). This is the SINGLE home of the MFU numerator
    (ISSUE 12): bench.py's headline MFU and obs/perf.py's runtime
    ``train.mfu`` both divide this figure by wall × chip peak, so the two
    are the same number at the same shape by construction. Counting
    required (not executed) flops is the standard MFU convention — kernel
    recompute must never inflate utilization."""
    return 12.0 * float(n_feats) * float(d) * float(n_members)


def serve_flush_plan(op: str, bucket: int, n_feats: int, d: int, *,
                     n_stack: int = 1, itemsize: int = 4) -> KernelPlan:
    """Roofline (hbm_bytes, mxu_flops, est_s) for ONE serving bucket
    dispatch (engine ``run_padded``): the dict params stream once per
    stacked member, the padded input and the result stream once. Used by
    ``obs/perf.py``'s serve probe for the predicted-vs-achieved gap; the
    serving ops are plain XLA programs, so the efficiency calibration is
    ``AUTODIFF_MXU_EFF`` (the measured XLA discount), and off-chip the
    prediction is the v5e reference number — the probe labels the backend
    so cpu rows are never read as on-chip."""
    n = max(1, int(n_stack))
    p = float(n_feats) * d * 4  # dict params (f32 resident)
    x = float(bucket) * (d if op != "decode" else n_feats) * itemsize
    out_w = {"encode": n_feats, "decode": d, "predict": d}.get(op, n_feats)
    c = float(bucket) * out_w * itemsize
    mad = 2.0 * bucket * n_feats * d  # one [bucket,d]x[d,n] matmul
    flops = {"encode": mad, "decode": mad, "predict": 2 * mad,
             "topk": mad}.get(op, mad) * n
    hbm = n * p + x + n * c
    return KernelPlan(path=None, hbm_bytes=hbm, mxu_flops=flops,
                      est_s=_est_s(hbm, flops, AUTODIFF_MXU_EFF),
                      reason=f"serve:{op}")


def path_cost(path: Optional[str], n_members: int, batch: int, n_feats: int,
              d: int, *, batch_itemsize: int = 4, n_mats: int = 1,
              moments_itemsize: int = 4, batch_tile: Optional[int] = None,
              feat_tile: Optional[int] = None,
              sentinel: bool = True,
              sharded: bool = False) -> tuple[float, float]:
    """(hbm_bytes, mxu_flops) for one whole step on this path — the table
    in the module docstring. ``path=None`` models XLA autodiff.
    ``sharded`` models the per-device step at the PER-DEVICE batch (ICI
    psum traffic is common to every fused path and drops out of the
    ranking); its one structural effect is the tied ``train_step``:
    sharded it is the grads-kernel + Adam/VJP-epilogue factoring, not
    the single-device one-kernel pass."""
    p = n_feats * d * 4
    pm = n_feats * d * moments_itemsize
    x = batch * d * batch_itemsize
    x4 = batch * d * 4
    c = batch * n_feats * 4
    adam = n_mats * (3 * p + 4 * pm)
    sent = (2 * p * n_mats) if sentinel else 0
    epilogue = n_mats * (3 * p + 4 * pm)
    mad = 2.0 * batch * n_feats * d  # one [B,d]x[d,n] matmul

    if path is None:  # autodiff
        per = x4 + 4 * c + 2 * p * n_mats + adam + sent
        flops = 6 * mad
    elif path == "two_stage":
        per = x + 2 * p * n_mats + adam + sent
        flops = 5 * mad
    elif path == "train_step":
        if n_mats == 1 and not sharded:
            # tied one-kernel pass + XLA delta-norm sentinel
            per = x + 2 * (p + 2 * pm) + (2 * p if sentinel else 0)
        else:  # grads kernel + fused Adam/VJP epilogue kernel (untied
            # always; tied under sharding — the psum sits between them)
            per = x + 2 * p * n_mats + epilogue
        flops = 5 * mad
    elif path in ("two_stage_tiled", "train_step_tiled"):
        bt = batch_tile or batch
        ft = feat_tile or n_feats
        fwd = (batch // bt) * p * n_mats + x + x4
        resid = 2 * x4 + x
        bwd = (n_feats // ft) * (x + x4) + 2 * p * n_mats
        per = fwd + resid + bwd
        if path == "two_stage_tiled":
            # grad norms are kernel-folded; the update norm stays XLA
            per += adam + (p * n_mats if sentinel else 0)
        else:
            per += epilogue
        flops = 6 * mad
    else:
        raise ValueError(f"unknown kernel path {path!r}")
    return float(n_members) * per, float(n_members) * flops


def _admit(path: str, batch: int, n_feats: int, d: int, *,
           batch_itemsize: int, compute_itemsize: int, n_mats: int,
           moments_itemsize: int, batch_tile: Optional[int],
           feat_tile: Optional[int],
           lane_rule: bool = True,
           sharded: bool = False) -> Optional[tuple[Optional[int],
                                                    Optional[int]]]:
    """(batch_tile, feat_tile) admission for one path, or None. Explicit
    tiles must themselves pass (same rule the kernels apply); an explicit
    feat_tile pins resolution to the TILED paths (it has no meaning for
    the untiled kernels). ``sharded``: the whole-step paths run the
    grads-kernel + epilogue-kernel factoring on every family, so the
    tied train_step admits by the two-stage rule + a dividing epilogue
    tile instead of the one-kernel working set."""
    if path in ("two_stage", "train_step") and feat_tile is not None:
        return None
    if path == "two_stage":
        if batch_tile is not None:
            ok = tile_fits(batch, batch_tile, n_feats, d, batch_itemsize,
                           compute_itemsize=compute_itemsize, n_mats=n_mats)
            return (batch_tile, None) if ok else None
        bt = pick_batch_tile(batch, n_feats, d, batch_itemsize=batch_itemsize,
                             compute_itemsize=compute_itemsize, n_mats=n_mats)
        return None if bt is None else (bt, None)
    if path == "train_step":
        if n_mats == 2 or sharded:
            # whole-step = the SAME grads kernel as two_stage plus the
            # feature-tiled Adam/VJP epilogue kernel (untied always;
            # both families under sharding, where the data-axis psum
            # runs between the two kernels)
            pair = _admit("two_stage", batch, n_feats, d,
                          batch_itemsize=batch_itemsize,
                          compute_itemsize=compute_itemsize, n_mats=n_mats,
                          moments_itemsize=moments_itemsize,
                          batch_tile=batch_tile, feat_tile=None)
            epi = (pick_epilogue_tile(n_feats, d) if n_mats == 2
                   else pick_tied_epilogue_tile(n_feats, d))
            if pair is None or epi is None:
                return None
            return pair
        if batch_tile is not None:
            ok = train_tile_fits(batch, batch_tile, n_feats, d,
                                 batch_itemsize, compute_itemsize=compute_itemsize,
                                 n_mats=n_mats, moments_itemsize=moments_itemsize)
            return (batch_tile, None) if ok else None
        bt = pick_train_step_tile(batch, n_feats, d,
                                  batch_itemsize=batch_itemsize,
                                  compute_itemsize=compute_itemsize,
                                  n_mats=n_mats,
                                  moments_itemsize=moments_itemsize)
        return None if bt is None else (bt, None)
    # tiled paths (lane_rule=False for interpret-mode buckets — same
    # relaxation prepare_tiled_batch applies, so resolution and the
    # kernels' own admission can never disagree)
    pair = pick_tiled_tiles(batch, n_feats, d, batch_itemsize=batch_itemsize,
                            compute_itemsize=compute_itemsize, n_mats=n_mats,
                            batch_tile=batch_tile, feat_tile=feat_tile,
                            lane_rule=lane_rule)
    if pair is None:
        return None
    if path == "train_step_tiled":
        epi = (pick_epilogue_tile(n_feats, d) if n_mats == 2
               else pick_tied_epilogue_tile(n_feats, d))
        if epi is None:
            return None
    return pair


def candidate_plans(*, n_members: int, batch: int, n_feats: int, d: int,
                    family: str, sharded: bool = False,
                    batch_itemsize: int = 4, compute_itemsize: int = 4,
                    moments_itemsize: int = 4,
                    batch_tile: Optional[int] = None,
                    feat_tile: Optional[int] = None,
                    sentinel: bool = True,
                    lane_rule: bool = True,
                    paths: Optional[tuple] = None) -> list[KernelPlan]:
    """Every VMEM-admissible fused plan for this shape, unranked."""
    n_mats = 2 if family == "untied" else 1
    allowed = paths if paths is not None else FAMILY_PATHS[family]
    if sharded:
        allowed = tuple(p for p in allowed if p in SHARDED_PATHS)
    out = []
    for path in allowed:
        pair = _admit(path, batch, n_feats, d, batch_itemsize=batch_itemsize,
                      compute_itemsize=compute_itemsize, n_mats=n_mats,
                      moments_itemsize=moments_itemsize,
                      batch_tile=batch_tile, feat_tile=feat_tile,
                      lane_rule=lane_rule, sharded=sharded)
        if pair is None:
            continue
        bt, ft = pair
        hbm, flops = path_cost(path, n_members, batch, n_feats, d,
                               batch_itemsize=batch_itemsize, n_mats=n_mats,
                               moments_itemsize=moments_itemsize,
                               batch_tile=bt, feat_tile=ft,
                               sentinel=sentinel, sharded=sharded)
        out.append(KernelPlan(path=path, batch_tile=bt, feat_tile=ft,
                              hbm_bytes=hbm, mxu_flops=flops,
                              est_s=_est_s(hbm, flops, KERNEL_MXU_EFF),
                              reason="roofline"))
    return out


def autodiff_plan(n_members: int, batch: int, n_feats: int, d: int, *,
                  batch_itemsize: int = 4, n_mats: int = 1,
                  moments_itemsize: int = 4, sentinel: bool = True,
                  reason: str = "no_admissible_tile") -> KernelPlan:
    hbm, flops = path_cost(None, n_members, batch, n_feats, d,
                           batch_itemsize=batch_itemsize, n_mats=n_mats,
                           moments_itemsize=moments_itemsize,
                           sentinel=sentinel)
    return KernelPlan(path=None, hbm_bytes=hbm, mxu_flops=flops,
                      est_s=_est_s(hbm, flops, AUTODIFF_MXU_EFF),
                      reason=reason)


def choose_plan(*, n_members: int, batch: int, n_feats: int, d: int,
                family: str, sharded: bool = False, batch_itemsize: int = 4,
                compute_itemsize: int = 4, moments_itemsize: int = 4,
                forced_path: Optional[str] = None,
                batch_tile: Optional[int] = None,
                feat_tile: Optional[int] = None,
                sentinel: bool = True,
                lane_rule: bool = True) -> KernelPlan:
    """The admission decision: lowest-modeled-time admissible fused plan
    (ties break by the KERNEL_PATHS preference order), the forced path if
    ``forced_path`` pins one, or the autodiff fallback plan (path=None,
    reason says why) when nothing admits. ``lane_rule=False`` relaxes the
    Mosaic lane rule on feature tiles for interpret-mode buckets, exactly
    as the kernels' own prepare_tiled_batch does."""
    n_mats = 2 if family == "untied" else 1
    paths = None
    if forced_path is not None:
        allowed = FAMILY_PATHS[family]
        if sharded:
            allowed = tuple(p for p in allowed if p in SHARDED_PATHS)
        if forced_path not in allowed:
            return autodiff_plan(
                n_members, batch, n_feats, d, batch_itemsize=batch_itemsize,
                n_mats=n_mats, moments_itemsize=moments_itemsize,
                sentinel=sentinel, reason=f"forced_unavailable:{forced_path}")
        paths = (forced_path,)
    plans = candidate_plans(
        n_members=n_members, batch=batch, n_feats=n_feats, d=d,
        family=family, sharded=sharded, batch_itemsize=batch_itemsize,
        compute_itemsize=compute_itemsize, moments_itemsize=moments_itemsize,
        batch_tile=batch_tile, feat_tile=feat_tile, sentinel=sentinel,
        lane_rule=lane_rule, paths=paths)
    if not plans:
        return autodiff_plan(
            n_members, batch, n_feats, d, batch_itemsize=batch_itemsize,
            n_mats=n_mats, moments_itemsize=moments_itemsize,
            sentinel=sentinel,
            reason=(f"forced_unfit:{forced_path}" if forced_path
                    else "no_admissible_tile"))
    best = min(plans, key=lambda pl: (pl.est_s, _PREFERENCE[pl.path]))
    if forced_path is not None:
        best = dataclasses.replace(best, reason="forced")
    return best
