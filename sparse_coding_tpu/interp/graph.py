"""Config-driven ablation-graph and feature-investigation drivers.

Consumers of `InterpGraphArgs` and `InvestigateArgs` (config.py) — the
counterparts of the reference's graph-interp entry points
(reference: config.py InterpGraphArgs:129-136, InvestigateArgs:137-143, used
by the interp_notebooks/ workflows and the missing ioi_feature_ident.py).
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Optional

import jax
import numpy as np

from sparse_coding_tpu.config import InterpGraphArgs, InvestigateArgs
from sparse_coding_tpu.interp.fragments import sample_fragments
from sparse_coding_tpu.resilience.atomic import atomic_write_text
from sparse_coding_tpu.metrics.intervention import (
    build_ablation_graph,
    build_ablation_graph_non_positional,
)
from sparse_coding_tpu.utils.artifacts import load_learned_dicts


def run_interp_graph(cfg: InterpGraphArgs, params, lm_cfg,
                     token_rows: np.ndarray, forward=None,
                     features_to_ablate=None, target_features=None) -> dict:
    """Build an ablation graph between the dicts named in cfg.dict_paths
    (one per layer in cfg.layers) and persist it as JSON."""
    if len(cfg.dict_paths) != len(cfg.layers):
        raise ValueError(
            f"need one dict per layer: {len(cfg.dict_paths)} paths for "
            f"{len(cfg.layers)} layers")
    models = {}
    for layer, path in zip(cfg.layers, cfg.dict_paths):
        ld, _ = load_learned_dicts(path)[0]
        models[(layer, cfg.layer_loc)] = ld

    fragments = sample_fragments(token_rows, cfg.fragment_len, cfg.n_fragments,
                                 seed=cfg.seed)
    tokens = jax.numpy.asarray(fragments)
    builder = (build_ablation_graph if cfg.positional
               else build_ablation_graph_non_positional)
    graph = builder(params, lm_cfg, models, tokens,
                    features_to_ablate=features_to_ablate,
                    target_features=target_features, forward=forward)

    out = Path(cfg.output_folder)
    out.mkdir(parents=True, exist_ok=True)
    serializable = {repr(k): v for k, v in graph.items()}
    atomic_write_text(out / "ablation_graph.json",
                      json.dumps(serializable, indent=2))
    return graph


def investigate_features(cfg: InvestigateArgs, params, lm_cfg,
                         token_rows: np.ndarray, decode_token,
                         forward=None) -> list[dict]:
    """Deep-dive specific features of one dict: interpretation records for
    exactly cfg.feature_indices (the single-feature investigation workflow)."""
    from sparse_coding_tpu.config import InterpArgs
    from sparse_coding_tpu.interp.run import run

    ld, _ = load_learned_dicts(cfg.learned_dict_path)[0]
    interp_cfg = InterpArgs(
        model_name=cfg.model_name, layer=cfg.layer, layer_loc=cfg.layer_loc,
        output_folder=cfg.output_folder, fragment_len=cfg.fragment_len,
        n_fragments=cfg.n_fragments, provider="offline", seed=cfg.seed,
        n_feats_to_explain=len(cfg.feature_indices) or 1)
    return run(ld, interp_cfg, params, lm_cfg, token_rows, decode_token,
               forward=forward,
               feature_indices=cfg.feature_indices or None)
