"""Auto-interpretation drivers and score persistence.

Re-design of the reference's `interpret()` loop and batch drivers
(reference: interpret.py:265-386 per-feature explain→simulate→score;
:414-688 folder/sweep/baseline/chunk drivers; :456-501 score readers).
Artifact layout mirrors the reference: `{output}/feature_{i}/explanation.txt`
+ `scores.json`, with skip-if-exists idempotence (interpret.py:267-269).
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Optional, Sequence

import jax
import numpy as np

from sparse_coding_tpu.config import InterpArgs
from sparse_coding_tpu.resilience.atomic import atomic_write_text
from sparse_coding_tpu.interp.client import ActivationRecord, Explainer, get_explainer
from sparse_coding_tpu.utils.artifacts import load_learned_dicts
from sparse_coding_tpu.interp.fragments import (
    FragmentActivations,
    TokenActivationLookup,
    build_fragment_activations,
    sample_fragments,
)


def correlation_score(true: np.ndarray, predicted: np.ndarray) -> float:
    """Pearson correlation between simulated and true activations — the
    reference's correlation scoring (interpret.py:350-358)."""
    t = np.asarray(true, np.float64).ravel()
    p = np.asarray(predicted, np.float64).ravel()
    if t.std() == 0 or p.std() == 0:
        return 0.0
    return float(np.corrcoef(t, p)[0, 1])


def _records_for(fragment_idx, feature: int, fa: FragmentActivations,
                 lookup: TokenActivationLookup, decode_token) -> list[ActivationRecord]:
    records = []
    for fi in np.asarray(fragment_idx):
        toks = [decode_token(int(t)) for t in np.asarray(fa.fragments[fi])]
        acts = [float(a) for a in lookup.tokens_activations(int(fi), feature)]
        records.append(ActivationRecord(tokens=toks, activations=acts))
    return records


def interpret_feature(feature: int, fa: FragmentActivations,
                      lookup: TokenActivationLookup, explainer: Explainer,
                      decode_token, top_k: int = 10, n_random: int = 10,
                      seed: int = 0) -> dict:
    """Explain one feature from its top fragments; score the explanation on
    top, random, and combined fragments (reference: interpret.py:265-386)."""
    top_idx, top_vals = fa.top_fragments(feature, top_k)
    rand_idx = fa.random_fragments(n_random, seed=seed + feature)

    top_records = _records_for(top_idx, feature, fa, lookup, decode_token)
    explanation = explainer.explain(top_records)

    def score(idx):
        true, pred = [], []
        for rec in _records_for(idx, feature, fa, lookup, decode_token):
            true.extend(rec.activations)
            pred.extend(explainer.simulate(explanation, rec.tokens))
        return correlation_score(np.asarray(true), np.asarray(pred))

    return {
        "feature": feature,
        "explanation": explanation,
        "top_score": score(top_idx),
        "random_score": score(rand_idx),
        "top_random_score": score(np.concatenate([np.asarray(top_idx),
                                                  np.asarray(rand_idx)])),
        "max_activation": float(top_vals[0]),
    }


def run(learned_dict, cfg: InterpArgs, params, lm_cfg, token_rows: np.ndarray,
        decode_token, forward=None,
        feature_indices: Optional[Sequence[int]] = None) -> list[dict]:
    """Main driver (reference: run(), interpret.py:388-411): build the
    fragment dataset once, interpret the requested features, persist
    per-feature artifacts."""
    out = Path(cfg.output_folder)
    out.mkdir(parents=True, exist_ok=True)
    explainer = get_explainer(cfg.provider,
                              **({} if cfg.provider == "offline" else
                                 {"explainer_model": cfg.explainer_model,
                                  "simulator_model": cfg.simulator_model}))

    fragments = sample_fragments(token_rows, cfg.fragment_len, cfg.n_fragments,
                                 seed=cfg.seed)
    fa, lookup = build_fragment_activations(
        params, lm_cfg, learned_dict, fragments, cfg.layer, cfg.layer_loc,
        batch_size=cfg.batch_size, forward=forward,
        scan_batches=cfg.scan_batches)

    if feature_indices is None:
        # features with the highest activation mass, as a sensible default
        mass = np.asarray(jax.device_get(fa.max_per_fragment)).sum(axis=0)
        feature_indices = list(np.argsort(-mass)[:cfg.n_feats_to_explain])

    results = []
    for feat in feature_indices:
        feat_dir = out / f"feature_{feat}"
        if (feat_dir / "scores.json").exists():  # idempotent re-runs
            results.append(json.loads((feat_dir / "scores.json").read_text()))
            continue
        rec = interpret_feature(int(feat), fa, lookup, explainer, decode_token,
                                top_k=cfg.top_k_fragments,
                                n_random=cfg.n_random_fragments, seed=cfg.seed)
        feat_dir.mkdir(parents=True, exist_ok=True)
        atomic_write_text(feat_dir / "explanation.txt", rec["explanation"])
        # scores.json is the per-feature completeness marker (idempotent
        # re-runs key off it above) — written last, atomically
        atomic_write_text(feat_dir / "scores.json", json.dumps(rec, indent=2))
        results.append(rec)
    atomic_write_text(out / "summary.json", json.dumps(results, indent=2))
    return results


def run_folder(dict_paths: Sequence[str], cfg: InterpArgs, params, lm_cfg,
               token_rows, decode_token, forward=None) -> dict[str, list]:
    """Interpret every saved dict artifact in a folder
    (reference: run_folder/run_from_grouped, interpret.py:414-455)."""
    all_results = {}
    for path in dict_paths:
        for i, (ld, hyper) in enumerate(load_learned_dicts(path)):
            sub_cfg = cfg.replace(output_folder=str(
                Path(cfg.output_folder) / f"{Path(path).stem}_{i}"))
            all_results[f"{path}:{i}"] = run(ld, sub_cfg, params, lm_cfg,
                                             token_rows, decode_token,
                                             forward=forward)
    return all_results


def interpret_across_baselines(baseline_root: str | Path, cfg: InterpArgs,
                               params, lm_cfg, token_rows, decode_token,
                               forward=None) -> dict[str, list]:
    """Interpret every baseline artifact under a sweep_baselines output tree
    (reference: interpret_across_baselines, interpret.py:541-580 — its
    multi-GPU queue+workers collapse into sequential jitted runs here).

    Layer-aware: artifacts under `l{N}_{loc}/` subfolders (the
    run_all_baselines layout) are interpreted at THEIR layer/loc, like the
    reference's folder-name parsing (interpret.py:552-558); outputs are
    namespaced by the artifact's relative path so same-named pkls from
    different layers never collide."""
    import re

    baseline_root = Path(baseline_root)
    all_results = {}
    for path in sorted(baseline_root.rglob("*.pkl")):
        rel = path.relative_to(baseline_root)
        m = re.match(r"l(\d+)_(\w+)", rel.parts[0]) if len(rel.parts) > 1 else None
        sub_cfg = cfg
        if m:
            sub_cfg = cfg.replace(layer=int(m.group(1)), layer_loc=m.group(2))
        ns = "_".join(rel.with_suffix("").parts)
        for i, (ld, hyper) in enumerate(load_learned_dicts(path)):
            member_cfg = sub_cfg.replace(output_folder=str(
                Path(cfg.output_folder) / f"{ns}_{i}"))
            all_results[f"{rel}:{i}"] = run(ld, member_cfg, params, lm_cfg,
                                            token_rows, decode_token,
                                            forward=forward)
    return all_results


def interpret_across_big_sweep(sweep_output: str | Path, cfg: InterpArgs,
                               params, lm_cfg, token_rows, decode_token,
                               forward=None) -> dict[str, list]:
    """Interpret the FINAL snapshot's dicts of a big sweep
    (reference: interpret_across_big_sweep, interpret.py:583-640)."""
    snapshots = sorted(Path(sweep_output).glob("_*"),
                       key=lambda p: int(p.name[1:]))
    if not snapshots:
        raise FileNotFoundError(f"no _N snapshots under {sweep_output}")
    paths = sorted(str(p) for p in snapshots[-1].glob("*_learned_dicts.pkl"))
    return run_folder(paths, cfg, params, lm_cfg, token_rows, decode_token,
                      forward=forward)


def interpret_across_chunks(sweep_output: str | Path, cfg: InterpArgs, params,
                            lm_cfg, token_rows, decode_token,
                            forward=None) -> dict[str, list]:
    """Time-series interpretation: interpret the SAME features at each saved
    training snapshot (`_N/` folders) of a sweep — how interpretability
    evolves over training (reference: interpret_across_chunks,
    interpret.py:643-688)."""
    sweep_output = Path(sweep_output)
    snapshots = sorted(sweep_output.glob("_*"), key=lambda p: int(p.name[1:]))
    results: dict[str, dict] = {}
    # per (artifact, member) pinned feature sets, so the series tracks the
    # SAME features of the SAME ensemble member across training
    pinned: dict[str, Sequence[int]] = {}
    for snap in snapshots:
        snap_results = {}
        for artifact in sorted(snap.glob("*_learned_dicts.pkl")):
            for i, (ld, hyper) in enumerate(load_learned_dicts(artifact)):
                member_key = f"{artifact.name}:{i}"
                sub_cfg = cfg.replace(output_folder=str(
                    Path(cfg.output_folder) / snap.name /
                    f"{artifact.stem}_{i}"))
                recs = run(ld, sub_cfg, params, lm_cfg, token_rows,
                           decode_token, forward=forward,
                           feature_indices=pinned.get(member_key))
                pinned.setdefault(member_key, [r["feature"] for r in recs])
                snap_results[member_key] = recs
        results[snap.name] = snap_results
    return results


def read_scores(output_folder: str | Path) -> dict[int, dict]:
    """Parse per-feature artifacts back (reference: read_scores,
    interpret.py:456-501)."""
    out = {}
    for feat_dir in sorted(Path(output_folder).glob("feature_*")):
        scores_path = feat_dir / "scores.json"
        if scores_path.exists():
            rec = json.loads(scores_path.read_text())
            out[int(rec["feature"])] = rec
    return out


def _load_lm(model_name: str):
    """(params, lm_cfg, decode_token, forward) for the CLI. `tiny-gptneox` /
    `tiny-gpt2` are hermetic random-weight models (no network; tokens decode
    to their ids) — the CLI analogue of the test-suite LMs; anything else
    resolves through the HF cache (lm/convert.load_model)."""
    if model_name.startswith("tiny-"):
        arch = model_name.removeprefix("tiny-")
        from sparse_coding_tpu.lm import gpt2, gptneox
        from sparse_coding_tpu.lm.model_config import tiny_test_config

        mod = {"gptneox": gptneox, "gpt2": gpt2}[arch]
        lm_cfg = tiny_test_config(arch)
        params = mod.init_params(jax.random.PRNGKey(0), lm_cfg)
        return params, lm_cfg, str, mod.forward
    from transformers import AutoTokenizer

    from sparse_coding_tpu.lm.convert import forward_fn, load_model

    params, lm_cfg = load_model(model_name)
    tok = AutoTokenizer.from_pretrained(model_name)
    return params, lm_cfg, (lambda t: tok.decode([t])), forward_fn(lm_cfg)


def main(argv=None) -> None:
    """`python -m sparse_coding_tpu.interp.run [subcommand] ...` — the
    reference's CLI dispatch (interpret.py:764-815):

      (default)       interpret cfg.learned_dict_path's dict(s)
      read_results    print collected scores for cfg.output_folder
      run_group       interpret every *.pkl under --target
      big_sweep       final-snapshot dicts of a sweep output tree (--target)
      all_baselines   every baseline artifact under --target
      chunks          same features across each training snapshot (--target)

    Token rows come from --tokens (a .npy saved by
    data.tokenize.save_token_dataset)."""
    import argparse
    import sys

    argv = list(sys.argv[1:] if argv is None else argv)
    sub = "interpret"
    if argv and not argv[0].startswith("-"):
        sub = argv.pop(0)
    known_subs = {"interpret", "read_results", "run_group", "big_sweep",
                  "all_baselines", "chunks"}
    if sub not in known_subs:
        raise SystemExit(f"unknown subcommand {sub!r}; one of {sorted(known_subs)}")

    pre = argparse.ArgumentParser(add_help=False)
    pre.add_argument("--tokens", default="", help=".npy token dataset")
    pre.add_argument("--target", default="", help="root folder for the batch "
                     "drivers (run_group/big_sweep/all_baselines/chunks)")
    extra, rest = pre.parse_known_args(argv)
    cfg = InterpArgs.from_cli(rest)

    if sub == "read_results":
        print(json.dumps(read_scores(cfg.output_folder), indent=2))
        return

    # all cheap argument validation BEFORE paying for token/LM loading
    if not extra.tokens:
        raise SystemExit("--tokens TOKENS.npy is required for this subcommand")
    if sub != "interpret" and not extra.target:
        raise SystemExit(f"--target ROOT is required for {sub}")
    if sub == "interpret" and not cfg.learned_dict_path:
        raise SystemExit("--learned_dict_path is required")

    from sparse_coding_tpu.data.tokenize import load_token_dataset

    token_rows = load_token_dataset(extra.tokens)
    params, lm_cfg, decode_token, forward = _load_lm(cfg.model_name)
    common = dict(params=params, lm_cfg=lm_cfg, token_rows=token_rows,
                  decode_token=decode_token, forward=forward)

    if sub == "interpret":
        results = run_folder([cfg.learned_dict_path], cfg, **common)
    elif sub == "run_group":
        paths = sorted(str(p) for p in Path(extra.target).rglob("*.pkl"))
        results = run_folder(paths, cfg, **common)
    elif sub == "big_sweep":
        results = interpret_across_big_sweep(extra.target, cfg, **common)
    elif sub == "all_baselines":
        results = interpret_across_baselines(extra.target, cfg, **common)
    else:  # chunks
        results = interpret_across_chunks(extra.target, cfg, **common)
    n = sum(len(v) for v in results.values())
    print(f"interp {sub}: {len(results)} dict(s), {n} feature records -> "
          f"{cfg.output_folder}")


def read_transform_scores(root: str | Path) -> dict[str, list[float]]:
    """Collect top_random scores per transform directory for comparison plots
    (reference: read_transform_scores, interpret.py:456-483)."""
    results = {}
    for transform_dir in sorted(Path(root).iterdir()):
        if not transform_dir.is_dir():
            continue
        scores = [rec["top_random_score"]
                  for rec in read_scores(transform_dir).values()]
        if scores:
            results[transform_dir.name] = scores
    return results


if __name__ == "__main__":
    main()
