"""Provider-neutral explainer/simulator clients.

The reference hard-wires OpenAI's neuron-explainer (GPT-4 explainer +
davinci simulator) and reads secrets.json AT IMPORT TIME
(reference: interpret.py:30-57,334-358) — SURVEY.md §7 explicitly says not to
replicate that. Here:

- `Explainer` protocol: explain(records) -> str and
  simulate(explanation, tokens) -> predicted activations;
- `OfflineExplainer`: deterministic token-overlap heuristic, so the whole
  interpretation pipeline (incl. scoring) runs and tests offline;
- `OpenAIExplainer`: lazy, opt-in; credentials are read only when
  constructed, never at import.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from pathlib import Path
from typing import Protocol, Sequence

import numpy as np


@dataclass
class ActivationRecord:
    """One fragment shown to the explainer: decoded tokens + that feature's
    per-token activations."""

    tokens: list[str]
    activations: list[float]


class Explainer(Protocol):
    def explain(self, records: Sequence[ActivationRecord]) -> str: ...

    def simulate(self, explanation: str, tokens: Sequence[str]) -> list[float]: ...


@dataclass
class OfflineExplainer:
    """Deterministic mock protocol: the 'explanation' is the set of tokens
    that most activate the feature; simulation predicts activation
    proportional to token membership. Good enough to exercise scoring
    end-to-end and to regression-test the pipeline without any API."""

    top_n_tokens: int = 5

    _MARKER = "activates on tokens: "

    def explain(self, records: Sequence[ActivationRecord]) -> str:
        weights: dict[str, float] = {}
        for rec in records:
            for tok, act in zip(rec.tokens, rec.activations):
                weights[tok] = weights.get(tok, 0.0) + float(act)
        top = sorted(weights, key=weights.get, reverse=True)[:self.top_n_tokens]
        # JSON-encoded token list: unambiguous even when tokens contain
        # commas/quotes (a plain comma-join mis-parses "','" tokens)
        return self._MARKER + json.dumps(top)

    def simulate(self, explanation: str, tokens: Sequence[str]) -> list[float]:
        listed = explanation.split(self._MARKER, 1)[-1]
        try:
            vocab = set(json.loads(listed))
        except json.JSONDecodeError:
            vocab = set()
        return [1.0 if t in vocab else 0.0 for t in tokens]


def normalize_activations(activations: Sequence[float],
                          max_activation: float) -> list[int]:
    """The neuron-explainer discretization: activations scaled to 0-10
    integers relative to the feature's max over the shown records
    (openai/automated-interpretability
    neuron_explainer/explanations/explanations.py; negative values clamp
    to 0)."""
    if max_activation <= 0:
        return [0] * len(activations)
    return [max(0, min(10, round(10 * float(a) / max_activation)))
            for a in activations]


def _records_block(records: Sequence[ActivationRecord],
                   max_activation: float) -> str:
    """token<tab>activation lines between <start>/<end> markers — the
    TokenActivationPairExplainer activation-record format."""
    parts = []
    for rec in records:
        acts = normalize_activations(rec.activations, max_activation)
        lines = "\n".join(f"{t}\t{a}" for t, a in zip(rec.tokens, acts))
        parts.append(f"<start>\n{lines}\n<end>")
    return "\n".join(parts)


# one-shot calibration example baked into the explainer prompt, mirroring
# the library's few-shot examples (same role structure; a compact original
# example rather than OpenAI's copyrighted ones)
_FEWSHOT_RECORDS = [ActivationRecord(
    tokens=["the", "cat", "sat", "on", "a", "mat"],
    activations=[0.0, 9.1, 0.0, 0.0, 0.0, 8.7])]
_FEWSHOT_EXPLANATION = "nouns referring to physical objects and animals"

EXPLAINER_PREAMBLE = (
    "We're studying neurons in a neural network. Each neuron looks for "
    "some particular thing in a short document. Look at the parts of the "
    "document the neuron activates for and summarize in a single sentence "
    "what the neuron is looking for. Don't list examples of words.\n\n"
    "The activation format is token<tab>activation. Activation values "
    "range from 0 to 10. A neuron finding what it's looking for is "
    "represented by a non-zero activation value. The higher the "
    "activation value, the stronger the match.")

SIMULATOR_PREAMBLE = (
    "We're studying neurons in a neural network. Each neuron looks for "
    "some particular thing in a short document. Look at an explanation of "
    "what the neuron does, and try to predict its activations on each "
    "particular token.\n\n"
    "The activation format is token<tab>activation, and activations range "
    "from 0 to 10. Most activations will be 0.")


def expected_values_from_logprobs(out_tokens: Sequence[str],
                                  top_logprobs: Sequence[dict],
                                  n_tokens: int) -> list[float]:
    """The neuron-explainer calibration: for each re-emitted
    `token<TAB>digit` line, the prediction is the EXPECTED value over the
    0-10 integers in the digit position's top-logprob distribution
    (automated-interpretability
    explanations/simulator.py::compute_expected_value) — not the argmax
    digit. Parsing anchors on the TAB line structure, never on document
    tokens (a fragment token like "2024" must not be mistaken for an
    activation); a line whose activation never parses contributes 0 at its
    slot, so alignment with the true activations is preserved. Missing
    tails pad 0."""
    import math

    def as_int(tok: str):
        tok = tok.strip()
        if tok.isdigit() and 0 <= int(tok) <= 10:
            return int(tok)
        return None

    def ev(dist, fallback: int) -> float:
        if not dist:
            return float(fallback)
        num, den = 0.0, 0.0
        for cand, lp in dist.items():
            v = as_int(cand)
            if v is not None:
                p = math.exp(lp)
                num += v * p
                den += p
        return num / den if den > 0 else float(fallback)

    def continuation(i: int, raw_digits: str):
        """A two-token number split like '1'+'0' (or a fused '\\t1' followed
        by '0'): if the NEXT token is a digit string whose concatenation
        still parses as a 0-10 activation, the number extends across the
        split. Returns the combined value, else None. Without this,
        '...\\t1','0' recorded 1 and dropped the 0 — understating exactly
        the max-activation (10) positions that drive the correlation score
        (ADVICE r4 #1). Newline boundaries end the number: a current token
        already carrying '\\n' ('1\\n'), or a next token whose digit sits
        AFTER a newline ('\\n0' — the next LINE's document token), must not
        merge."""
        if "\n" in raw_digits or i + 1 >= len(out_tokens):
            return None
        nxt = out_tokens[i + 1].rstrip("\n")  # '0\n' is digit + line end
        if nxt and nxt.isdigit():
            return as_int(raw_digits.strip() + nxt)
        return None

    evs: list[float] = []
    expect_digit = False
    i = 0
    while i < len(out_tokens) and len(evs) < n_tokens:
        tok = out_tokens[i]
        # a truncated logprobs array (e.g. around a stop sequence) degrades
        # to fallback values, it must not crash the scoring call
        dist = top_logprobs[i] if i < len(top_logprobs) else {}
        if expect_digit:
            v = as_int(tok)
            if v is not None:  # the digit token right after the tab
                combined = continuation(i, tok)
                if combined is not None:
                    # multi-token number: no single logprob position holds
                    # the value, so record it literally
                    evs.append(float(combined))
                    i += 1  # consume the continuation token
                else:
                    evs.append(ev(dist, v))
                expect_digit = False
            elif "\n" in tok:  # line ended without a parseable activation
                evs.append(0.0)
                expect_digit = False
            i += 1
            continue
        if "\t" in tok:
            tail = tok.rsplit("\t", 1)[1]
            v = as_int(tail)
            if tail and v is not None:  # tab+digit fused into one token
                combined = continuation(i, tail)
                if combined is not None:
                    evs.append(float(combined))
                    i += 1
                else:
                    evs.append(ev(dist, v))
            else:
                expect_digit = True
        i += 1
    evs += [0.0] * (n_tokens - len(evs))
    return evs


@dataclass
class OpenAIExplainer:
    """The reference's OpenAI neuron-explainer protocol
    (interpret.py:334-358: TokenActivationPairExplainer +
    ExplanationNeuronSimulator/UncalibratedNeuronSimulator), replicated:

    - explainer: chat few-shot in the library's role structure, activation
      records discretized to 0-10 relative to the max shown activation;
    - simulator: "all at once" completion that re-emits each token line
      with a predicted activation, read back as the EXPECTED VALUE over
      the 0-10 digits in each position's logprob distribution — the
      library's calibration trick, which the correlation score then
      consumes (interp/run.py::correlation_score, the reference's
      preferred ev_correlation_score).

    Lazy: importing this module never touches credentials; construction
    requires them explicitly or via env. `_client` is injectable for
    hermetic tests (tests/test_interp_tasks.py uses a fake)."""

    explainer_model: str = "gpt-4"
    simulator_model: str = "gpt-3.5-turbo-instruct"
    api_key: str | None = None
    max_tokens: int = 256
    _client: object = field(default=None, repr=False)

    def __post_init__(self):
        import os

        if self._client is not None:
            return  # injected (tests)
        key = self.api_key or os.environ.get("OPENAI_API_KEY")
        if not key:
            raise ValueError("OpenAIExplainer needs api_key or OPENAI_API_KEY")
        try:
            import openai

            self._client = openai.OpenAI(api_key=key)
        except ImportError as e:
            raise ImportError("openai package not installed; use "
                              "OfflineExplainer or install openai") from e

    def explainer_messages(self, records: Sequence[ActivationRecord]) -> list[dict]:
        max_act = max((max(r.activations, default=0.0) for r in records),
                      default=0.0)
        few_max = max(_FEWSHOT_RECORDS[0].activations)
        ask = ("\n\nNeuron 2\nActivations:\n"
               + _records_block(records, max_act)
               + "\n\nExplanation of neuron 2 behavior: this neuron "
                 "activates on")
        return [
            {"role": "system", "content": EXPLAINER_PREAMBLE},
            {"role": "user",
             "content": ("\n\nNeuron 1\nActivations:\n"
                         + _records_block(_FEWSHOT_RECORDS, few_max)
                         + "\n\nExplanation of neuron 1 behavior: this "
                           "neuron activates on")},
            {"role": "assistant", "content": " " + _FEWSHOT_EXPLANATION},
            {"role": "user", "content": ask},
        ]

    def explain(self, records: Sequence[ActivationRecord]) -> str:
        resp = self._client.chat.completions.create(
            model=self.explainer_model,
            messages=self.explainer_messages(records),
            max_tokens=self.max_tokens, temperature=1.0)
        return resp.choices[0].message.content.strip()

    def simulator_prompt(self, explanation: str,
                         tokens: Sequence[str]) -> str:
        unknowns = "\n".join(f"{t}\tunknown" for t in tokens)
        return (SIMULATOR_PREAMBLE
                + "\n\nNeuron 2\nExplanation of neuron 2 behavior: this "
                  f"neuron activates on {explanation}\n"
                  "Activations:\n<start>\n" + unknowns + "\n<end>\n\n"
                  "Now write the same list again, replacing each "
                  "\"unknown\" with the predicted activation:\n<start>\n")

    def simulate(self, explanation: str, tokens: Sequence[str]) -> list[float]:
        resp = self._client.completions.create(
            model=self.simulator_model,
            prompt=self.simulator_prompt(explanation, tokens),
            max_tokens=8 * len(tokens) + 16, temperature=0.0,
            logprobs=5, stop=["<end>"])
        lp = resp.choices[0].logprobs
        return expected_values_from_logprobs(
            lp.tokens, lp.top_logprobs, len(tokens))


def get_explainer(provider: str, **kwargs) -> Explainer:
    if provider == "offline":
        return OfflineExplainer()
    if provider == "openai":
        return OpenAIExplainer(**kwargs)
    raise ValueError(f"unknown interpretation provider {provider!r}")
