"""Provider-neutral explainer/simulator clients.

The reference hard-wires OpenAI's neuron-explainer (GPT-4 explainer +
davinci simulator) and reads secrets.json AT IMPORT TIME
(reference: interpret.py:30-57,334-358) — SURVEY.md §7 explicitly says not to
replicate that. Here:

- `Explainer` protocol: explain(records) -> str and
  simulate(explanation, tokens) -> predicted activations;
- `OfflineExplainer`: deterministic token-overlap heuristic, so the whole
  interpretation pipeline (incl. scoring) runs and tests offline;
- `OpenAIExplainer`: lazy, opt-in; credentials are read only when
  constructed, never at import.
"""

from __future__ import annotations

import json
from dataclasses import dataclass, field
from pathlib import Path
from typing import Protocol, Sequence

import numpy as np


@dataclass
class ActivationRecord:
    """One fragment shown to the explainer: decoded tokens + that feature's
    per-token activations."""

    tokens: list[str]
    activations: list[float]


class Explainer(Protocol):
    def explain(self, records: Sequence[ActivationRecord]) -> str: ...

    def simulate(self, explanation: str, tokens: Sequence[str]) -> list[float]: ...


@dataclass
class OfflineExplainer:
    """Deterministic mock protocol: the 'explanation' is the set of tokens
    that most activate the feature; simulation predicts activation
    proportional to token membership. Good enough to exercise scoring
    end-to-end and to regression-test the pipeline without any API."""

    top_n_tokens: int = 5

    _MARKER = "activates on tokens: "

    def explain(self, records: Sequence[ActivationRecord]) -> str:
        weights: dict[str, float] = {}
        for rec in records:
            for tok, act in zip(rec.tokens, rec.activations):
                weights[tok] = weights.get(tok, 0.0) + float(act)
        top = sorted(weights, key=weights.get, reverse=True)[:self.top_n_tokens]
        # JSON-encoded token list: unambiguous even when tokens contain
        # commas/quotes (a plain comma-join mis-parses "','" tokens)
        return self._MARKER + json.dumps(top)

    def simulate(self, explanation: str, tokens: Sequence[str]) -> list[float]:
        listed = explanation.split(self._MARKER, 1)[-1]
        try:
            vocab = set(json.loads(listed))
        except json.JSONDecodeError:
            vocab = set()
        return [1.0 if t in vocab else 0.0 for t in tokens]


@dataclass
class OpenAIExplainer:
    """Thin client over the OpenAI API mirroring the reference's
    TokenActivationPairExplainer + UncalibratedNeuronSimulator roles
    (interpret.py:334-358). Lazy: importing this module never touches
    credentials; construction requires them explicitly or via env."""

    explainer_model: str = "gpt-4"
    simulator_model: str = "gpt-3.5-turbo-instruct"
    api_key: str | None = None
    max_tokens: int = 256
    _client: object = field(default=None, repr=False)

    def __post_init__(self):
        import os

        key = self.api_key or os.environ.get("OPENAI_API_KEY")
        if not key:
            raise ValueError("OpenAIExplainer needs api_key or OPENAI_API_KEY")
        try:
            import openai

            self._client = openai.OpenAI(api_key=key)
        except ImportError as e:
            raise ImportError("openai package not installed; use "
                              "OfflineExplainer or install openai") from e

    def explain(self, records: Sequence[ActivationRecord]) -> str:
        lines = []
        for rec in records:
            pairs = [f"{t}\t{a:.2f}" for t, a in zip(rec.tokens, rec.activations)]
            lines.append("\n".join(pairs))
        prompt = ("We're studying a neuron in a language model. For each "
                  "excerpt below, each line is a token and the neuron's "
                  "activation on it. Summarize in one phrase what the neuron "
                  "fires on.\n\n" + "\n---\n".join(lines) + "\n\nExplanation:")
        resp = self._client.chat.completions.create(
            model=self.explainer_model,
            messages=[{"role": "user", "content": prompt}],
            max_tokens=self.max_tokens)
        return resp.choices[0].message.content.strip()

    def simulate(self, explanation: str, tokens: Sequence[str]) -> list[float]:
        prompt = (f"A neuron fires on: {explanation}\nFor each token below, "
                  "output a number 0-10 for how strongly the neuron fires, "
                  "one per line, nothing else.\n" + "\n".join(tokens))
        resp = self._client.completions.create(
            model=self.simulator_model, prompt=prompt,
            max_tokens=4 * len(tokens), temperature=0.0)
        vals = []
        for line in resp.choices[0].text.strip().splitlines():
            try:
                vals.append(float(line.strip()))
            except ValueError:
                vals.append(0.0)
        vals += [0.0] * (len(tokens) - len(vals))
        return vals[:len(tokens)]


def get_explainer(provider: str, **kwargs) -> Explainer:
    if provider == "offline":
        return OfflineExplainer()
    if provider == "openai":
        return OpenAIExplainer(**kwargs)
    raise ValueError(f"unknown interpretation provider {provider!r}")
