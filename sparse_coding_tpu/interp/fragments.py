"""Feature-activation fragment dataset.

Re-design of the reference's `make_feature_activation_dataset`
(reference: interpret.py:82-212): the reference streams openwebtext, takes one
random 64-token fragment per document, runs the LM, encodes with the
dictionary, and materializes a giant pandas DataFrame (cached as HDF,
:215-262). Here only the per-fragment per-feature MAXES ([N, F]) stay
resident — the top-k selection input — while per-token activations are
recomputed lazily for just the fragments a feature's explanation actually
reads (top-k + random ≈ 20 of N), so device memory never scales with
n_fragments × fragment_len × n_feats.
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import flax.struct as struct
import jax
import jax.numpy as jnp
import numpy as np

from sparse_coding_tpu.lm.hooks import tap_name
from sparse_coding_tpu.lm.model_config import LMConfig
from sparse_coding_tpu.models.learned_dict import LearnedDict

Array = jax.Array


def sample_fragments(token_rows: np.ndarray, fragment_len: int,
                     n_fragments: int, seed: int = 0) -> np.ndarray:
    """One random fragment per row (reference: interpret.py:141-150 takes a
    random 64-token window per document)."""
    if token_rows.shape[1] < fragment_len:
        raise ValueError(
            f"token rows have length {token_rows.shape[1]} < fragment_len "
            f"{fragment_len}; harvest with a longer context or lower "
            "cfg.fragment_len")
    rng = np.random.default_rng(seed)
    n = min(n_fragments, token_rows.shape[0])
    rows = rng.permutation(token_rows.shape[0])[:n]
    out = np.zeros((n, fragment_len), token_rows.dtype)
    for i, r in enumerate(rows):
        max_start = token_rows.shape[1] - fragment_len
        s = rng.integers(0, max_start + 1) if max_start > 0 else 0
        out[i] = token_rows[r, s:s + fragment_len]
    return out


class FragmentActivations(struct.PyTreeNode):
    """Per-feature interpretation inputs: fragments + per-fragment maxes."""

    fragments: Array  # [N, L] token ids
    max_per_fragment: Array  # [N, F] max activation of each feature per fragment
    n_feats: int = struct.field(pytree_node=False, default=0)

    def top_fragments(self, feature: int, k: int) -> tuple[Array, Array]:
        """(fragment indices, their max activations) for one feature."""
        k = min(k, int(self.fragments.shape[0]))
        vals, idx = jax.lax.top_k(self.max_per_fragment[:, feature], k)
        return idx, vals

    def random_fragments(self, k: int, seed: int = 0) -> Array:
        rng = np.random.default_rng(seed)
        return jnp.asarray(rng.permutation(self.fragments.shape[0])[:k])


class TokenActivationLookup:
    """Lazy per-token activations: recomputes codes for just the requested
    fragments (a handful per feature) instead of holding [N, L, F] on device.
    The host cache is LRU-bounded so interpreting thousands of features over
    a large fragment pool can't grow without limit."""

    def __init__(self, fragments: Array, encode_batch: Callable[[Array], Array],
                 cache_size: int = 512):
        import functools

        self._fragments = fragments
        self._encode_batch = encode_batch
        self._codes_for = functools.lru_cache(maxsize=max(1, cache_size))(
            self._compute_codes)

    def _compute_codes(self, fragment_idx: int) -> np.ndarray:
        c = self._encode_batch(self._fragments[fragment_idx][None, :])
        return np.asarray(jax.device_get(c[0]))

    def tokens_activations(self, fragment_idx: int, feature: int) -> np.ndarray:
        return self._codes_for(int(fragment_idx))[:, feature]


def make_fragment_encode_fns(params, lm_cfg: LMConfig, model: LearnedDict,
                             layer: int, layer_loc: str = "residual",
                             forward=None):
    """The two jitted fragment programs: `encode_batch` (tokens[b,s] →
    per-token codes [b,s,n]) and `window_maxes` (a [K,b,s] token stack →
    per-fragment maxes [K*b,n], K forwards fused into one device program
    with the max reduced in-scan). Factored out so the TPU AOT-lowering
    gate traces exactly what build_fragment_activations dispatches."""
    if forward is None:
        from sparse_coding_tpu.lm.convert import forward_fn
        forward = forward_fn(lm_cfg)
    tap = tap_name(layer, layer_loc)

    @jax.jit
    def encode_batch(toks):
        _, tapped = forward(params, toks, lm_cfg, taps=(tap,),
                            stop_at_layer=layer + 1)
        acts = tapped[tap]
        b, s, d = acts.shape
        return model.encode(model.center(acts.reshape(b * s, d))).reshape(b, s, -1)

    @jax.jit
    def window_maxes(tok_stack):  # [K, b, s] -> [K*b, n_feats]
        _, m = jax.lax.scan(
            lambda _, toks: (None, jnp.max(encode_batch(toks), axis=1)),
            None, tok_stack)
        return m.reshape(-1, m.shape[-1])

    return encode_batch, window_maxes


def build_fragment_activations(
    params, lm_cfg: LMConfig, model: LearnedDict, fragments: np.ndarray,
    layer: int, layer_loc: str = "residual", batch_size: int = 64,
    forward=None, scan_batches: int = 1,
) -> tuple[FragmentActivations, TokenActivationLookup]:
    """Run the LM over ALL fragments (tail batch included), keeping only the
    per-fragment maxes on device; returns the maxes plus a lazy lookup.

    `scan_batches=K` fuses K fragment batches into one device program with
    the per-fragment max reduced INSIDE the scan (the reference's 50k
    fragments at batch 20 are ~2500 separate dispatches, interpret.py:169;
    through the axon tunnel each costs ~54 ms — data/harvest.py has the
    same lever; InterpArgs.scan_batches plumbs it). Results are identical
    to K=1; the sub-window tail runs on the single-batch program (its own
    compilations: one for a full batch, one more if a partial final batch
    exists)."""
    if fragments.shape[0] == 0:
        raise ValueError("no fragments to process")
    encode_batch, window_maxes = make_fragment_encode_fns(
        params, lm_cfg, model, layer, layer_loc, forward)

    maxes = []
    n = fragments.shape[0]
    window_rows = batch_size * max(1, scan_batches)
    lo = 0
    while lo < n:
        if scan_batches > 1 and n - lo >= window_rows:
            stack = jnp.asarray(fragments[lo:lo + window_rows].reshape(
                scan_batches, batch_size, -1))
            maxes.append(window_maxes(stack))
            lo += window_rows
        else:
            c = encode_batch(jnp.asarray(fragments[lo:lo + batch_size]))
            maxes.append(jnp.max(c, axis=1))
            lo += batch_size
    max_per_fragment = jnp.concatenate(maxes, axis=0)
    fragments_dev = jnp.asarray(fragments)
    fa = FragmentActivations(fragments=fragments_dev,
                             max_per_fragment=max_per_fragment,
                             n_feats=int(max_per_fragment.shape[-1]))
    return fa, TokenActivationLookup(fragments_dev, encode_batch)
