"""The ensemble training engine.

TPU-native replacement for the reference's `FunctionalEnsemble`
(reference: autoencoders/ensemble.py:68-193), which imitates JAX in PyTorch by
stacking param pytrees and running `torch.vmap(torch.func.grad(loss))` +
`torch.vmap(optimizer.update)` with in-place state copies. Here the whole
step — per-member grads, Adam update, parameter application — is one pure
function, vmapped over the ensemble axis and jitted once; XLA fuses the
elementwise optimizer math into the matmul epilogues.

Sharding model (replaces cluster_runs.py's process-per-GPU scheduler and
huge_batch_size.py's gloo DDP):
- mesh axes ("model", "data");
- stacked params/opt-state sharded over "model" along the leading ensemble
  axis (each shard owns N/mesh_model members — the moral equivalent of one
  reference worker process, with zero host code);
- the activation batch sharded over "data"; per-member grads/losses are
  reduced over "data" by XLA-inserted collectives riding ICI;
- placement resolves through the partition rule layer
  (parallel/partition.py, docs/ARCHITECTURE.md §19), and since r15 the
  WHOLE-STEP fused paths run on the mesh too: grads kernel →
  psum("data") → fused Adam/VJP epilogue kernel
  (make_fullfused_step_sharded), so auto mode keeps whole-step on
  meshes and the two-stage multi-chip penalty is gone by construction.

Members whose loss has *static* hyperparameters that change compiled shapes
(e.g. TopK's k) are bucketed into sub-ensembles — the analogue of the
reference's `no_stacking` Python loop (ensemble.py:100-116), but each bucket
is still vmapped internally.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Sequence

import flax.struct as struct
import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh

from sparse_coding_tpu.models.signatures import AuxData
from sparse_coding_tpu.utils.trees import stack_trees, tree_index

Array = jax.Array
Pytree = Any

# version portability for the container's baked toolchain: older optax
# names safe_increment safe_int32_increment; older jax exposes shard_map
# under jax.experimental with check_rep instead of check_vma
_safe_increment = getattr(optax, "safe_increment",
                          getattr(optax, "safe_int32_increment", None))

# every kernel path _resolve_step can land on (ops/roofline.py is the
# single source; re-exported here because the path label is engine API —
# bench/tune variants, obs counters, and the parity-coverage lint key on it)
from sparse_coding_tpu.ops.roofline import KERNEL_PATHS  # noqa: E402


from sparse_coding_tpu.parallel import partition  # noqa: E402
from sparse_coding_tpu.parallel.mesh import compat_shard_map as _shard_map  # noqa: E402

_STATIC_TYPES = (int, float, bool, str, type(None))

StaticBuffers = tuple[tuple[str, Any], ...]  # hashable, jit-static


def split_buffers(buffers: Pytree) -> tuple[Pytree, StaticBuffers]:
    """Partition a member's buffers into (array leaves, static leaves).

    Static leaves (plain Python scalars, e.g. TopK's k) become compile-time
    constants shared by every member of a bucket; array leaves are stacked and
    vmapped over.
    """
    arrays = {}
    statics = {}
    for name, leaf in buffers.items():
        if isinstance(leaf, _STATIC_TYPES):
            statics[name] = leaf
        else:
            arrays[name] = leaf
    return arrays, tuple(sorted(statics.items()))


def merge_buffers(arrays: Pytree, statics: StaticBuffers) -> dict:
    merged = dict(arrays)
    merged.update(dict(statics))
    return merged


class EnsembleState(struct.PyTreeNode):
    """Device state for one vmapped bucket: everything stacked on axis 0."""

    params: Pytree
    buffers: Pytree
    opt_state: Pytree
    lrs: Array  # [N] per-member learning rate
    step: Array  # scalar step counter
    # [N] bool live-mask (docs/ARCHITECTURE.md §16): False freezes a
    # member — its params and optimizer state pass through every step
    # unchanged (a bitwise no-op for True members). Host-owned: only the
    # training guardian (train/guardian.py) flips it; the in-graph
    # sentinel additionally skips any single step whose loss/grads went
    # non-finite without touching this mask.
    live: Optional[Array] = None
    static_buffers: StaticBuffers = struct.field(pytree_node=False, default=())
    sig_name: str = struct.field(pytree_node=False, default="")

    @property
    def n_members(self) -> int:
        return int(self.lrs.shape[0])


def adam_optimizer(b1: float = 0.9, b2: float = 0.999, eps: float = 1e-8) -> optax.GradientTransformation:
    """Bare Adam transform; the per-member lr is applied by the step function
    (matching torchopt.adam semantics used at reference ensemble.py:85,
    update = lr·m̂/(√v̂ + eps))."""
    return optax.scale_by_adam(b1=b1, b2=b2, eps=eps, eps_root=0.0)


def _fused_aux(losses: dict, activity: Array) -> AuxData:
    """AuxData assembly shared by every fused path (loss fields match the
    autodiff path, locked by tests/test_torch_loss_parity.py). An optional
    "bias_decay" loss entry (untied family) is folded into the total and
    reported under the autodiff path's "l_bias_decay" key."""
    total = losses["mse"] + losses["l1"]
    loss_fields = {"l_reconstruction": losses["mse"], "l_l1": losses["l1"]}
    if "bias_decay" in losses:
        total = total + losses["bias_decay"]
        loss_fields["l_bias_decay"] = losses["bias_decay"]
    return AuxData(
        losses={"loss": total, **loss_fields},
        l0=losses["l0"],
        feat_activity=activity.astype(jnp.int32))


def _select_members(ok: Array, new: Pytree, old: Pytree) -> Pytree:
    """Per-member select over stacked [N, ...] trees: where ``ok[i]`` the
    new leaf slice, else the old one. ``jnp.where`` on a boolean mask is
    an exact element copy, so a True member's result is BITWISE the
    unguarded update (property-tested, tests/test_ensemble.py) and a
    False member's state — params, Adam moments, bias-correction count —
    passes through untouched, NaN/Inf in the discarded branch included."""

    def sel(n, o):
        mask = ok.reshape(ok.shape + (1,) * (n.ndim - 1))
        return jnp.where(mask, n, o)

    return jax.tree.map(sel, new, old)


def _member_delta_norm(new: Pytree, old: Pytree) -> Array:
    """Per-member global L2 norm of (new − old) over stacked [N, ...]
    trees — the whole-step kernels' grad-norm surrogate: any non-finite
    leaf in the kernel's output propagates into this one [N] reduction,
    so finiteness of the whole update is checkable without re-scanning
    every tensor with isfinite."""

    def sq(n, o):
        d = n - o
        return jnp.sum(jnp.square(d), axis=tuple(range(1, d.ndim)))

    return jnp.sqrt(sum(jax.tree.leaves(jax.tree.map(sq, new, old))))


def _sentinel_finite(loss: Array, *norms: Array) -> Array:
    """[N] step-finite flag from the per-member loss and norm reductions
    (each norm already folds a whole tree's non-finites into one value)."""
    finite = jnp.isfinite(loss)
    for n in norms:
        finite = finite & jnp.isfinite(n)
    return finite


def _apply_fused_updates(optimizer, losses, grads, activity,
                         params, opt_state, lrs, live=None,
                         kernel_gnorm=None):
    """Shared tail of the two-stage fused steps: vmapped per-member Adam
    update from kernel-produced grads + shared AuxData assembly. With
    ``live`` (the state's [N] live-mask) the in-graph anomaly sentinel is
    woven in: per-member grad/update global norms, a step-finite flag,
    and a member-select that freezes quarantined or non-finite members —
    all device-side, nothing synced to the host (§16). ``kernel_gnorm``
    ([N], tiled producers): the grad norm was already folded into the
    kernel's backward epilogue, so the XLA ``optax.global_norm`` pass
    over the [N, n, d] grads is skipped — divergence safety stays free
    at high MFU (ISSUE 11); the reported grad_norm is then the
    KERNEL-grad norm (pre normalization-VJP — see fused_sae_tiled)."""

    sentinel = live is not None
    need_gn = sentinel and kernel_gnorm is None

    def member_update(g, opt_state, params, lr):
        norms = (optax.global_norm(g),) if need_gn else ()
        updates, opt_state = optimizer.update(g, opt_state, params)
        updates = jax.tree.map(lambda u: -lr * u, updates)
        if sentinel:
            norms += (optax.global_norm(updates),)
        return optax.apply_updates(params, updates), opt_state, norms

    new_params, new_opt, norms = jax.vmap(member_update)(
        grads, opt_state, params, lrs)
    aux = _fused_aux(losses, activity)
    if not sentinel:  # the pre-guardian step, bit for bit
        return new_params, new_opt, aux
    if need_gn:
        gn, un = norms
    else:
        gn, un = kernel_gnorm, norms[0]
    finite = _sentinel_finite(aux.losses["loss"], gn, un)
    ok = live & finite
    return (_select_members(ok, new_params, params),
            _select_members(ok, new_opt, opt_state),
            aux.replace(finite=finite, grad_norm=gn))


def _tied_producer(batch_tile, interpret, compute_dtype):
    """(params, buffers, batch, total_batch, psum_axis) -> (losses, grads,
    activity, gnorm) via the tied kernel
    (ops/fused_sae.fused_tied_sae_loss_and_grads; gnorm is None — the
    untiled kernels leave the sentinel norms to XLA). Serves both the plain
    tied family and the masked family (FunctionalMaskedTiedSAE): when the
    bucket's buffers carry a coef_mask it rides into the kernel as one
    extra [N, n] operand."""
    from sparse_coding_tpu.ops.fused_sae import fused_tied_sae_loss_and_grads

    def producer(params, buffers, batch, total_batch=None, psum_axis=None):
        return (*fused_tied_sae_loss_and_grads(
            {"encoder": params["encoder"],
             "encoder_bias": params["encoder_bias"]},
            buffers["l1_alpha"], batch, batch_tile=batch_tile,
            interpret=interpret, total_batch=total_batch,
            compute_dtype=compute_dtype, psum_axis=psum_axis,
            coef_mask=buffers.get("coef_mask")), None)

    return producer


def _untied_producer(batch_tile, interpret, compute_dtype):
    """Untied-family producer (ops/fused_sae.fused_untied_sae_loss_and_grads);
    any bias_decay is exact — the decay term is applied outside the kernel,
    AFTER the in-wrapper psum, so it counts once per member, not once per
    data shard."""
    from sparse_coding_tpu.ops.fused_sae import fused_untied_sae_loss_and_grads

    def producer(params, buffers, batch, total_batch=None, psum_axis=None):
        return (*fused_untied_sae_loss_and_grads(
            params, buffers["l1_alpha"], buffers["bias_decay"], batch,
            batch_tile=batch_tile, interpret=interpret,
            total_batch=total_batch, compute_dtype=compute_dtype,
            psum_axis=psum_axis), None)

    return producer


def _tied_tiled_producer(batch_tile, feat_tile, interpret, compute_dtype):
    """Feature-axis-tiled tied/masked producer
    (ops/fused_sae_tiled.fused_tied_sae_tiled_loss_and_grads) — the path
    the canonical ratio-16/96 sweep shapes resolve to. Returns the
    kernel-epilogue grad norm as the 4th element (None under shard_map)."""
    from sparse_coding_tpu.ops.fused_sae_tiled import (
        fused_tied_sae_tiled_loss_and_grads)

    def producer(params, buffers, batch, total_batch=None, psum_axis=None):
        return fused_tied_sae_tiled_loss_and_grads(
            {"encoder": params["encoder"],
             "encoder_bias": params["encoder_bias"]},
            buffers["l1_alpha"], batch, batch_tile=batch_tile,
            feat_tile=feat_tile, interpret=interpret,
            total_batch=total_batch, compute_dtype=compute_dtype,
            psum_axis=psum_axis, coef_mask=buffers.get("coef_mask"))

    return producer


def _untied_tiled_producer(batch_tile, feat_tile, interpret, compute_dtype):
    """Feature-axis-tiled untied producer
    (ops/fused_sae_tiled.fused_untied_sae_tiled_loss_and_grads)."""
    from sparse_coding_tpu.ops.fused_sae_tiled import (
        fused_untied_sae_tiled_loss_and_grads)

    def producer(params, buffers, batch, total_batch=None, psum_axis=None):
        return fused_untied_sae_tiled_loss_and_grads(
            params, buffers["l1_alpha"], buffers["bias_decay"], batch,
            batch_tile=batch_tile, feat_tile=feat_tile, interpret=interpret,
            total_batch=total_batch, compute_dtype=compute_dtype,
            psum_axis=psum_axis)

    return producer


def _stamp_inputs_finite(aux: AuxData, batch: Array,
                         sentinel: bool) -> AuxData:
    """Fold the batch-finite flag into the aux (device-side scalar; the
    guardian's data-corruption incident class). Computed at the step
    wrapper, outside any vmap/shard_map, so it is one replicated scalar."""
    if not sentinel:
        return aux
    return aux.replace(inputs_finite=jnp.all(jnp.isfinite(batch)))


def make_fused_step(
    producer: Callable,
    optimizer: optax.GradientTransformation,
    donate: bool = True,
    sentinel: bool = True,
) -> Callable[[EnsembleState, Array], tuple[EnsembleState, AuxData]]:
    """Single-device fused-kernel step: loss + exact grads come from one
    Pallas pass (via `producer`, see _tied_producer/_untied_producer) instead
    of vmap(value_and_grad); the optimizer update stays vmapped optax."""

    def step(state: EnsembleState, batch: Array) -> tuple[EnsembleState, AuxData]:
        losses, grads, activity, gnorm = producer(state.params,
                                                  state.buffers, batch)
        params, opt_state, aux = _apply_fused_updates(
            optimizer, losses, grads, activity,
            state.params, state.opt_state, state.lrs,
            live=state.live if sentinel else None,
            kernel_gnorm=gnorm if sentinel else None)
        aux = _stamp_inputs_finite(aux, batch, sentinel)
        new_state = state.replace(params=params, opt_state=opt_state,
                                  step=state.step + 1)
        return new_state, aux

    return jax.jit(step, donate_argnums=(0,) if donate else ())


def make_fused_step_sharded(
    producer: Callable,
    optimizer: optax.GradientTransformation,
    mesh: Mesh,
    donate: bool = True,
    sentinel: bool = True,
) -> Callable[[EnsembleState, Array], tuple[EnsembleState, AuxData]]:
    """Mesh-composed fused step: the flagship multi-chip configuration
    (replacing /root/reference/cluster_runs.py:100-157's all-GPUs-training
    scheduler at full scale). Under shard_map each device owns N/mesh_model
    members ("model" axis) and B/mesh_data batch rows ("data" axis) and runs
    the SAME Pallas kernel as the single-chip path on its local slice — the
    kernel normalizes by the GLOBAL batch size, so one psum over "data"
    (inside the producer: batch-independent loss terms must be added after
    it) yields exact full-batch losses/grads, then the optimizer update runs
    locally per member shard. HBM/ICI traffic per step: x once into VMEM,
    one [N_local, n, d] grad reduce-scatter-shaped psum riding ICI."""

    def local_step(params, buffers, opt_state, lrs, live, local_batch,
                   total_batch):
        # tiled producers return gnorm=None on sharded calls by
        # construction (the kernel epilogue's per-shard partial norms
        # don't psum into the true norm), so the sentinel here always
        # takes the XLA norm over the post-psum grads
        losses, grads, activity, gnorm = producer(params, buffers,
                                                  local_batch,
                                                  total_batch=total_batch,
                                                  psum_axis="data")
        # the post-psum losses/grads are identical on every data shard, so
        # the sentinel's finite flags — and therefore the member-select —
        # agree across the whole mesh by construction
        return _apply_fused_updates(optimizer, losses, grads, activity,
                                    params, opt_state, lrs,
                                    live=live if sentinel else None,
                                    kernel_gnorm=gnorm if sentinel else None)

    def step(state: EnsembleState, batch: Array) -> tuple[EnsembleState, AuxData]:
        sharded = _shard_map(
            functools.partial(local_step, total_batch=batch.shape[0]),
            mesh,
            in_specs=(partition.MEMBER, partition.MEMBER, partition.MEMBER,
                      partition.MEMBER, partition.MEMBER, partition.BATCH),
            out_specs=(partition.MEMBER, partition.MEMBER, partition.MEMBER))
        params, opt_state, aux = sharded(
            state.params, state.buffers, state.opt_state, state.lrs,
            state.live, batch)
        aux = _stamp_inputs_finite(aux, batch, sentinel)
        new_state = state.replace(params=params, opt_state=opt_state,
                                  step=state.step + 1)
        return new_state, aux

    return jax.jit(step, donate_argnums=(0,) if donate else ())


def _guard_fullfused(state: EnsembleState, params, opt_state, aux, batch,
                     sentinel: bool, un=None, gn=None):
    """Sentinel tail shared by the whole-step kernel paths. Default: grads
    never leave the kernel, so the per-member update-delta norm (any
    NaN/Inf in the kernel's new params propagates into it) stands in for
    the grad norm, and the member-select freezes quarantined/non-finite
    members — one elementwise pass over the [N, n, d] tensors the kernel
    already wrote. Paths whose epilogue kernels fold the norms in
    (ISSUE 11: the feature-tiled epilogues, the untied Adam/VJP kernel)
    pass them as ``un``/``gn`` and skip even that pass."""
    if not sentinel or state.live is None:
        return params, opt_state, aux
    if un is None:
        un = _member_delta_norm(params, state.params)
    norms = (un,) if gn is None else (gn, un)
    finite = _sentinel_finite(aux.losses["loss"], *norms)
    ok = state.live & finite
    return (_select_members(ok, params, state.params),
            _select_members(ok, opt_state, state.opt_state),
            _stamp_inputs_finite(
                aux.replace(finite=finite,
                            grad_norm=un if gn is None else gn),
                batch, True))


def _bias_adam_update(bias, db, opt, lrs, bc1, bc2, b1, b2, eps):
    """Exact optax-Adam on the [N, n] bias in XLA (negligible traffic next
    to the matrices the kernels carry) — the SINGLE home of this formula
    for every whole-step builder below, so the tiled and untiled paths can
    never diverge optimizer-wise. Returns (new_bias, mu_b, nu_b)."""
    mu_b = b1 * opt.mu["encoder_bias"] + (1.0 - b1) * db
    nu_b = b2 * opt.nu["encoder_bias"] + (1.0 - b2) * db * db
    bias2 = bias - lrs[:, None] * (mu_b / bc1[:, None]) / (
        jnp.sqrt(nu_b / bc2[:, None]) + eps)
    return bias2, mu_b, nu_b


def make_fullfused_tied_step(
    adam_hypers: tuple[float, float, float],
    donate: bool = True,
    interpret: bool = False,
    batch_tile: Optional[int] = None,
    compute_dtype: str = "float32",
    sentinel: bool = True,
) -> Callable[[EnsembleState, Array], tuple[EnsembleState, AuxData]]:
    """Single-device tied-SAE step where the WHOLE step — normalization,
    loss, exact grads, normalization VJP, and the optax-Adam update — runs in
    one Pallas pass (ops/fused_sae.fused_tied_sae_train_step). No XLA
    prologue/epilogue remains; optimizer-state DMA hides under the kernel's
    MXU time. Bias corrections are precomputed here exactly as optax's
    scale_by_adam does, so this step is numerically the two-stage path."""
    from sparse_coding_tpu.ops.fused_sae import (
        fused_tied_sae_train_step, pick_train_step_tile, prepare_kernel_batch)

    b1, b2, eps = adam_hypers

    def step(state: EnsembleState, batch: Array) -> tuple[EnsembleState, AuxData]:
        opt = state.opt_state
        raw_batch = batch
        batch, tile = prepare_kernel_batch(
            batch, state.params["encoder"].shape[1],
            state.params["encoder"].shape[2], batch_tile, compute_dtype,
            picker=functools.partial(
                pick_train_step_tile,
                moments_itemsize=opt.mu["encoder"].dtype.itemsize))
        count_inc = _safe_increment(opt.count)
        bc1 = 1.0 - b1 ** count_inc
        bc2 = 1.0 - b2 ** count_inc
        losses, e2, bias2, mu_e, nu_e, mu_b, nu_b, activity = (
            fused_tied_sae_train_step(
                state.params["encoder"], state.params["encoder_bias"],
                opt.mu["encoder"], opt.nu["encoder"],
                opt.mu["encoder_bias"], opt.nu["encoder_bias"],
                state.buffers["l1_alpha"], state.lrs, bc1, bc2, batch,
                batch_tile=tile, interpret=interpret,
                compute_dtype=compute_dtype, b1=b1, b2=b2, eps=eps))
        params = {"encoder": e2, "encoder_bias": bias2}
        opt_state = opt._replace(
            count=count_inc,
            mu={"encoder": mu_e, "encoder_bias": mu_b},
            nu={"encoder": nu_e, "encoder_bias": nu_b})
        aux = _fused_aux(losses, activity)
        params, opt_state, aux = _guard_fullfused(
            state, params, opt_state, aux, raw_batch, sentinel)
        new_state = state.replace(params=params, opt_state=opt_state,
                                  step=state.step + 1)
        return new_state, aux

    return jax.jit(step, donate_argnums=(0,) if donate else ())


def make_fullfused_untied_step(
    adam_hypers: tuple[float, float, float],
    donate: bool = True,
    interpret: bool = False,
    batch_tile: Optional[int] = None,
    compute_dtype: str = "float32",
    sentinel: bool = True,
) -> Callable[[EnsembleState, Array], tuple[EnsembleState, AuxData]]:
    """Single-device untied-SAE whole-step path: TWO Pallas passes and no XLA
    prologue/epilogue on the big matrices. Pass 1 (fused_untied_sae_grads)
    normalizes the decoder in-kernel and produces losses + exact grads; pass
    2 (fused_adam_vjp_update, feature-tiled) chains dWn through the
    normalization VJP and applies the exact optax-Adam update to encoder and
    decoder — one HBM read+write per tensor. A single-kernel variant (as the
    tied family has) would keep 12 double-buffered [n, d] blocks resident
    and exceeds VMEM at canonical shapes, hence the two-pass design. Bias
    (+ its decay term) updates stay in XLA: [N, n] traffic is negligible and
    the safe-norm reduction spans the full feature axis, which pass 2 tiles.
    Numerically identical to the two-stage path (same kernels' grad math,
    same optax formulas)."""
    from sparse_coding_tpu.ops.fused_sae import (
        fused_adam_vjp_update,
        fused_untied_sae_grads,
        pick_epilogue_tile,
        prepare_kernel_batch,
        untied_bias_decay_terms,
    )

    b1, b2, eps = adam_hypers

    def step(state: EnsembleState, batch: Array) -> tuple[EnsembleState, AuxData]:
        e = state.params["encoder"]
        dec = state.params["decoder"]
        bias = state.params["encoder_bias"]
        n_feats, d = e.shape[1], e.shape[2]
        raw_batch = batch
        batch, tile = prepare_kernel_batch(batch, n_feats, d, batch_tile,
                                           compute_dtype, n_mats=2)
        ftile = pick_epilogue_tile(n_feats, d)
        opt = state.opt_state
        count_inc = _safe_increment(opt.count)
        bc1 = 1.0 - b1 ** count_inc
        bc2 = 1.0 - b2 ** count_inc
        losses, de, dwn, db, activity = fused_untied_sae_grads(
            e, dec, bias, state.buffers["l1_alpha"], batch,
            batch_tile=tile, interpret=interpret,
            compute_dtype=compute_dtype)
        decay_loss, db = untied_bias_decay_terms(
            bias, state.buffers["bias_decay"], db)
        losses = dict(losses, bias_decay=decay_loss)
        e2, mu_e, nu_e, d2, mu_d, nu_d, un_sq = fused_adam_vjp_update(
            e, de, opt.mu["encoder"], opt.nu["encoder"],
            dec, dwn, opt.mu["decoder"], opt.nu["decoder"],
            state.lrs, bc1, bc2, ftile=ftile, interpret=interpret,
            b1=b1, b2=b2, eps=eps)
        bias2, mu_b, nu_b = _bias_adam_update(bias, db, opt, state.lrs,
                                              bc1, bc2, b1, b2, eps)
        params = {"encoder": e2, "encoder_bias": bias2, "decoder": d2}
        opt_state = opt._replace(
            count=count_inc,
            mu={"encoder": mu_e, "encoder_bias": mu_b, "decoder": mu_d},
            nu={"encoder": nu_e, "encoder_bias": nu_b, "decoder": nu_d})
        aux = _fused_aux(losses, activity)
        # update norm = kernel-epilogue matrix term + the (tiny, [N, n])
        # bias delta — no XLA pass over the big tensors (ISSUE 11)
        un = jnp.sqrt(un_sq + jnp.sum(jnp.square(bias2 - bias), axis=-1))
        params, opt_state, aux = _guard_fullfused(
            state, params, opt_state, aux, raw_batch, sentinel, un=un)
        new_state = state.replace(params=params, opt_state=opt_state,
                                  step=state.step + 1)
        return new_state, aux

    return jax.jit(step, donate_argnums=(0,) if donate else ())


def make_tiled_step(
    family: str,
    optimizer: optax.GradientTransformation,
    batch_tile: int,
    feat_tile: int,
    mesh: Optional[Mesh] = None,
    donate: bool = True,
    interpret: bool = False,
    compute_dtype: str = "float32",
    sentinel: bool = True,
) -> Callable[[EnsembleState, Array], tuple[EnsembleState, AuxData]]:
    """Two-stage FEATURE-AXIS-TILED step (ISSUE 11): grads from the
    flash-style tiled kernel pair (ops/fused_sae_tiled.py — the path the
    canonical ratio-16/96 shapes resolve to), optimizer update in vmapped
    optax. The sentinel's grad norm arrives from the backward kernel's
    epilogue (single-device; sharded falls back to the post-psum XLA
    norm). ``family``: "tied" | "masked_tied" | "untied"."""
    make_producer = (_untied_tiled_producer if family == "untied"
                     else _tied_tiled_producer)
    producer = make_producer(batch_tile, feat_tile, interpret, compute_dtype)
    if mesh is not None:
        return make_fused_step_sharded(producer, optimizer, mesh,
                                       donate=donate, sentinel=sentinel)
    return make_fused_step(producer, optimizer, donate=donate,
                           sentinel=sentinel)


def make_fullfused_tiled_step(
    family: str,
    adam_hypers: tuple[float, float, float],
    batch_tile: int,
    feat_tile: int,
    donate: bool = True,
    interpret: bool = False,
    compute_dtype: str = "float32",
    sentinel: bool = True,
) -> Callable[[EnsembleState, Array], tuple[EnsembleState, AuxData]]:
    """Whole-step FEATURE-AXIS-TILED path (single device, ISSUE 11): the
    tiled grads kernels followed by the feature-tiled Adam/normalization-
    VJP epilogue kernel — the Adam moment blocks stream through VMEM in
    [ftile, d] tiles, so the whole-step path now exists at ANY n_feats
    (the one-kernel tied path needs the full matrix resident and dies at
    exactly the canonical high-ratio shapes). Both sentinel norms come
    out of kernel epilogues: grad norm from the backward kernel, update
    norm from the Adam/VJP kernel (+ the [N, n] bias delta in XLA) — no
    extra pass over any [N, n, d] tensor. Bias (+ decay term) updates
    stay XLA, exactly as in make_fullfused_untied_step. Numerically the
    two-stage tiled path (same grad kernels, same optax formulas)."""
    from sparse_coding_tpu.ops.fused_sae import (
        fused_adam_vjp_update,
        fused_tied_adam_vjp_update,
        pick_epilogue_tile,
        pick_tied_epilogue_tile,
        untied_bias_decay_terms,
    )
    from sparse_coding_tpu.ops.fused_sae_tiled import (
        prepare_tiled_batch,
        tiled_tied_sae_grads,
        tiled_untied_sae_grads,
    )

    if family not in ("tied", "untied"):
        raise ValueError(
            f"no whole-step tiled path for family {family!r} (the masked "
            "family's coef_mask rides the two-stage kernels only)")
    b1, b2, eps = adam_hypers
    tied = family == "tied"

    def step(state: EnsembleState, batch: Array) -> tuple[EnsembleState, AuxData]:
        e = state.params["encoder"]
        bias = state.params["encoder_bias"]
        n_feats, d = e.shape[1], e.shape[2]
        raw_batch = batch
        batch2, bt, ft = prepare_tiled_batch(
            batch, n_feats, d, batch_tile, feat_tile, compute_dtype,
            n_mats=1 if tied else 2, lane_rule=not interpret)
        opt = state.opt_state
        count_inc = _safe_increment(opt.count)
        bc1 = 1.0 - b1 ** count_inc
        bc2 = 1.0 - b2 ** count_inc
        if tied:
            losses, dw, db, activity, grad_sq = tiled_tied_sae_grads(
                e, bias, state.buffers["l1_alpha"], batch2, batch_tile=bt,
                feat_tile=ft, interpret=interpret,
                compute_dtype=compute_dtype)
            e2, mu_e, nu_e, un_sq = fused_tied_adam_vjp_update(
                e, dw, opt.mu["encoder"], opt.nu["encoder"], state.lrs,
                bc1, bc2, ftile=pick_tied_epilogue_tile(n_feats, d),
                interpret=interpret, b1=b1, b2=b2, eps=eps)
        else:
            dec = state.params["decoder"]
            losses, de, dwn, db, activity, grad_sq = tiled_untied_sae_grads(
                e, dec, bias, state.buffers["l1_alpha"], batch2,
                batch_tile=bt, feat_tile=ft, interpret=interpret,
                compute_dtype=compute_dtype)
            decay_loss, db = untied_bias_decay_terms(
                bias, state.buffers["bias_decay"], db)
            losses = dict(losses, bias_decay=decay_loss)
            e2, mu_e, nu_e, d2, mu_d, nu_d, un_sq = fused_adam_vjp_update(
                e, de, opt.mu["encoder"], opt.nu["encoder"], dec, dwn,
                opt.mu["decoder"], opt.nu["decoder"], state.lrs, bc1, bc2,
                ftile=pick_epilogue_tile(n_feats, d), interpret=interpret,
                b1=b1, b2=b2, eps=eps)
        bias2, mu_b, nu_b = _bias_adam_update(bias, db, opt, state.lrs,
                                              bc1, bc2, b1, b2, eps)
        if tied:
            params = {"encoder": e2, "encoder_bias": bias2}
            mu = {"encoder": mu_e, "encoder_bias": mu_b}
            nu = {"encoder": nu_e, "encoder_bias": nu_b}
        else:
            params = {"encoder": e2, "encoder_bias": bias2, "decoder": d2}
            mu = {"encoder": mu_e, "encoder_bias": mu_b, "decoder": mu_d}
            nu = {"encoder": nu_e, "encoder_bias": nu_b, "decoder": nu_d}
        opt_state = opt._replace(count=count_inc, mu=mu, nu=nu)
        aux = _fused_aux(losses, activity)
        gn = jnp.sqrt(grad_sq)
        un = jnp.sqrt(un_sq + jnp.sum(jnp.square(bias2 - bias), axis=-1))
        params, opt_state, aux = _guard_fullfused(
            state, params, opt_state, aux, raw_batch, sentinel, un=un, gn=gn)
        new_state = state.replace(params=params, opt_state=opt_state,
                                  step=state.step + 1)
        return new_state, aux

    return jax.jit(step, donate_argnums=(0,) if donate else ())


def make_fullfused_step_sharded(
    family: str,
    adam_hypers: tuple[float, float, float],
    mesh: Mesh,
    tiled: bool = False,
    batch_tile: Optional[int] = None,
    feat_tile: Optional[int] = None,
    donate: bool = True,
    interpret: bool = False,
    compute_dtype: str = "float32",
    sentinel: bool = True,
) -> Callable[[EnsembleState, Array], tuple[EnsembleState, AuxData]]:
    """Mesh-composed WHOLE-STEP fused path (ISSUE 15): the sharded twin of
    make_fullfused_untied_step / make_fullfused_tiled_step, closing the
    two-stage multi-chip penalty by construction. Under compat_shard_map
    each device runs the grads kernel (untiled two-stage kernels, or the
    feature-tiled pair when ``tiled``) on its local batch slice with the
    GLOBAL batch denominator, ONE psum over "data" yields exact full-batch
    losses/grads, and then the feature-tiled Adam/normalization-VJP
    epilogue kernel applies the exact optax update to the member shard —
    no XLA optimizer pass touches the [N, n, d] tensors. The data-axis
    psum sits exactly BETWEEN the two kernels, which is why the
    single-kernel tied train step cannot shard but this factoring can
    (the untied path was already factored this way; see
    make_fullfused_untied_step). Sentinel norms stay kernel-folded: the
    update norm comes out of the epilogue kernel's accumulator (+ the
    tiny [N, n] bias delta in XLA), and because the post-psum grads are
    identical on every data shard, the epilogue — and therefore the
    finite flags and the member-select freeze — agrees across the whole
    mesh by construction; the guardian's per-member quarantine
    (train/guardian.py) then needs consensus only across HOSTS, which
    ``parallel.agree_any`` already provides. Numerically identical to the
    sharded two-stage path (same grad kernels, same optax formulas;
    parity locked by tests/test_sharding.py)."""
    from sparse_coding_tpu.ops.fused_sae import (
        fused_adam_vjp_update,
        fused_tied_adam_vjp_update,
        fused_tied_sae_grads,
        fused_untied_sae_grads,
        pick_epilogue_tile,
        pick_tied_epilogue_tile,
        prepare_kernel_batch,
        untied_bias_decay_terms,
    )
    from sparse_coding_tpu.ops.fused_sae_tiled import (
        prepare_tiled_batch,
        tiled_tied_sae_grads,
        tiled_untied_sae_grads,
    )

    if family not in ("tied", "untied"):
        raise ValueError(
            f"no sharded whole-step path for family {family!r} (the masked "
            "family's coef_mask rides the two-stage kernels only)")
    b1, b2, eps = adam_hypers
    tied = family == "tied"

    def local_step(params, buffers, opt_state, lrs, live, local_batch,
                   total_batch):
        e = params["encoder"]
        bias = params["encoder_bias"]
        n_feats, d = e.shape[1], e.shape[2]
        ftile = (pick_tied_epilogue_tile if tied
                 else pick_epilogue_tile)(n_feats, d)
        if ftile is None:
            raise ValueError(
                f"no dividing epilogue feature tile for n_feats={n_feats}, "
                f"d={d}; use the sharded two-stage path")
        # grads kernel on the local slice, GLOBAL loss denominator
        if tiled:
            batch2, bt, ft = prepare_tiled_batch(
                local_batch, n_feats, d, batch_tile, feat_tile,
                compute_dtype, n_mats=1 if tied else 2,
                lane_rule=not interpret)
            if tied:
                losses, dw, db, activity, _ = tiled_tied_sae_grads(
                    e, bias, buffers["l1_alpha"], batch2, batch_tile=bt,
                    feat_tile=ft, interpret=interpret,
                    total_batch=total_batch, compute_dtype=compute_dtype)
            else:
                losses, de, dwn, db, activity, _ = tiled_untied_sae_grads(
                    e, params["decoder"], bias, buffers["l1_alpha"], batch2,
                    batch_tile=bt, feat_tile=ft, interpret=interpret,
                    total_batch=total_batch, compute_dtype=compute_dtype)
        else:
            batch2, bt = prepare_kernel_batch(
                local_batch, n_feats, d, batch_tile, compute_dtype,
                n_mats=1 if tied else 2)
            if tied:
                losses, dw, db, activity = fused_tied_sae_grads(
                    e, bias, buffers["l1_alpha"], batch2, batch_tile=bt,
                    interpret=interpret, total_batch=total_batch,
                    compute_dtype=compute_dtype)
            else:
                losses, de, dwn, db, activity = fused_untied_sae_grads(
                    e, params["decoder"], bias, buffers["l1_alpha"], batch2,
                    batch_tile=bt, interpret=interpret,
                    total_batch=total_batch, compute_dtype=compute_dtype)
        # THE psum: per-shard partial sums -> exact full-batch losses/grads,
        # identical on every data shard from here on. The kernel-epilogue
        # grad_sq (tiled producers) is a per-shard partial and is discarded
        # — sum-of-squares of partials is not the square of the sum.
        if tied:
            losses, dw, db, activity = jax.lax.psum(
                (losses, dw, db, activity), "data")
        else:
            losses, de, dwn, db, activity = jax.lax.psum(
                (losses, de, dwn, db, activity), "data")
            # batch-independent terms count once per member, AFTER the psum
            decay_loss, db = untied_bias_decay_terms(
                bias, buffers["bias_decay"], db)
            losses = dict(losses, bias_decay=decay_loss)
        # fused Adam/normalization-VJP epilogue on the member shard
        opt = opt_state
        count_inc = _safe_increment(opt.count)
        bc1 = 1.0 - b1 ** count_inc
        bc2 = 1.0 - b2 ** count_inc
        if tied:
            e2, mu_e, nu_e, un_sq = fused_tied_adam_vjp_update(
                e, dw, opt.mu["encoder"], opt.nu["encoder"], lrs, bc1, bc2,
                ftile=ftile, interpret=interpret, b1=b1, b2=b2, eps=eps)
            new_params = {"encoder": e2}
            mu = {"encoder": mu_e}
            nu = {"encoder": nu_e}
        else:
            e2, mu_e, nu_e, d2, mu_d, nu_d, un_sq = fused_adam_vjp_update(
                e, de, opt.mu["encoder"], opt.nu["encoder"],
                params["decoder"], dwn, opt.mu["decoder"], opt.nu["decoder"],
                lrs, bc1, bc2, ftile=ftile, interpret=interpret,
                b1=b1, b2=b2, eps=eps)
            new_params = {"encoder": e2, "decoder": d2}
            mu = {"encoder": mu_e, "decoder": mu_d}
            nu = {"encoder": nu_e, "decoder": nu_d}
        bias2, mu_b, nu_b = _bias_adam_update(bias, db, opt, lrs, bc1, bc2,
                                              b1, b2, eps)
        new_params["encoder_bias"] = bias2
        mu["encoder_bias"] = mu_b
        nu["encoder_bias"] = nu_b
        new_opt = opt._replace(count=count_inc, mu=mu, nu=nu)
        aux = _fused_aux(losses, activity)
        if not sentinel or live is None:
            return new_params, new_opt, aux
        # sentinel, kernel-folded (no extra pass over [N, n, d]): update
        # norm from the epilogue accumulator + the [N, n] bias delta; the
        # post-psum inputs make every data shard's verdict identical, so
        # the member-select agrees across the mesh by construction
        un = jnp.sqrt(un_sq + jnp.sum(jnp.square(bias2 - bias), axis=-1))
        finite = _sentinel_finite(aux.losses["loss"], un)
        ok = live & finite
        return (_select_members(ok, new_params, params),
                _select_members(ok, new_opt, opt),
                aux.replace(finite=finite, grad_norm=un))

    def step(state: EnsembleState, batch: Array) -> tuple[EnsembleState, AuxData]:
        sharded = _shard_map(
            functools.partial(local_step, total_batch=batch.shape[0]),
            mesh,
            in_specs=(partition.MEMBER, partition.MEMBER, partition.MEMBER,
                      partition.MEMBER, partition.MEMBER, partition.BATCH),
            out_specs=(partition.MEMBER, partition.MEMBER, partition.MEMBER))
        params, opt_state, aux = sharded(
            state.params, state.buffers, state.opt_state, state.lrs,
            state.live, batch)
        aux = _stamp_inputs_finite(aux, batch, sentinel)
        new_state = state.replace(params=params, opt_state=opt_state,
                                  step=state.step + 1)
        return new_state, aux

    return jax.jit(step, donate_argnums=(0,) if donate else ())


def make_fused_tied_step(optimizer, donate=True, interpret=False,
                         batch_tile=None, compute_dtype="float32",
                         sentinel=True):
    return make_fused_step(
        _tied_producer(batch_tile, interpret, compute_dtype), optimizer,
        donate=donate, sentinel=sentinel)


def make_fused_tied_step_sharded(optimizer, mesh, donate=True, interpret=False,
                                 batch_tile=None, compute_dtype="float32",
                                 sentinel=True):
    return make_fused_step_sharded(
        _tied_producer(batch_tile, interpret, compute_dtype), optimizer, mesh,
        donate=donate, sentinel=sentinel)


def make_fused_untied_step(optimizer, donate=True, interpret=False,
                           batch_tile=None, compute_dtype="float32",
                           sentinel=True):
    return make_fused_step(
        _untied_producer(batch_tile, interpret, compute_dtype), optimizer,
        donate=donate, sentinel=sentinel)


def make_fused_untied_step_sharded(optimizer, mesh, donate=True,
                                   interpret=False, batch_tile=None,
                                   compute_dtype="float32", sentinel=True):
    return make_fused_step_sharded(
        _untied_producer(batch_tile, interpret, compute_dtype), optimizer,
        mesh, donate=donate, sentinel=sentinel)


def can_use_fused_untied_step(sig: Any, members,
                              interpret: bool = False) -> bool:
    """Untied fused-path preconditions: plain "sae" signature whose members
    carry exactly the param/buffer structure the kernel computes gradients
    for (a name match alone could admit a subclassed signature with extra
    trainable params, silently dropping their grads), + TPU backend (or
    interpret mode). bias_decay needs no value gate — its term lives outside
    the kernel. VMEM tile admission happens per-batch in Ensemble."""
    if getattr(sig, "signature_name", None) != "sae":
        return False
    if not (interpret or jax.default_backend() == "tpu"):
        return False
    params0, buffers0 = members[0]
    return (set(params0) == {"encoder", "encoder_bias", "decoder"}
            and {"l1_alpha", "bias_decay"} <= set(buffers0))


def can_use_fused_tied_step(sig: Any, members, interpret: bool = False) -> bool:
    """Fused path preconditions checkable at construction: tied SAE (plain,
    with identity centering and zero bias decay) OR the masked family
    (FunctionalMaskedTiedSAE — the kernel takes its coef_mask as one extra
    operand; its loss has no centering/bias-decay terms to gate on), TPU
    backend (or interpret mode for tests). The VMEM-fitting batch tile is
    checked against the REAL batch on the first step (Ensemble.step_batch),
    not guessed here."""
    import numpy as np

    name = getattr(sig, "signature_name", None)
    if name not in ("tied_sae", "masked_tied_sae"):
        return False
    if not interpret and jax.default_backend() != "tpu":
        return False
    params0, buffers0 = members[0]
    if set(params0) != {"encoder", "encoder_bias"}:
        return False  # same structure guard as the untied gate
    if name == "masked_tied_sae":
        return "coef_mask" in buffers0
    d = params0["encoder"].shape[1]
    for _, b in members:
        if float(jnp.max(jnp.abs(b.get("bias_decay", jnp.zeros(()))))) != 0.0:
            return False
        if not (np.allclose(b["center_rot"], np.eye(d))
                and np.allclose(b["center_trans"], 0.0)
                and np.allclose(b["center_scale"], 1.0)):
            return False
    return True


def make_train_step(
    sig: Any,
    optimizer: optax.GradientTransformation,
    statics: StaticBuffers = (),
    donate: bool = True,
    sentinel: bool = True,
) -> Callable[[EnsembleState, Array], tuple[EnsembleState, AuxData]]:
    """Build the jitted (state, batch) -> (state, aux) step for a signature.

    One minibatch is shared by every member (the reference expands it across
    the ensemble axis, ensemble.py:175-181 — under vmap with in_axes=None the
    broadcast is free). With ``sentinel`` (the default) the in-graph anomaly
    sentinel rides the same program (§16): per-member grad/update norms and
    finite flags fold into the returned aux, and a member whose step went
    non-finite — or whose ``state.live`` flag the guardian cleared — keeps
    its params and optimizer state bit-identically unchanged."""

    def member_step(params, buffers, opt_state, lr, batch):
        def loss_fn(p):
            return sig.loss(p, merge_buffers(buffers, statics), batch)

        (_, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(params)
        norms = (optax.global_norm(grads),) if sentinel else ()
        updates, opt_state = optimizer.update(grads, opt_state, params)
        updates = jax.tree.map(lambda u: -lr * u, updates)
        if sentinel:
            norms += (optax.global_norm(updates),)
        params = optax.apply_updates(params, updates)
        return params, opt_state, aux, norms

    def step(state: EnsembleState, batch: Array) -> tuple[EnsembleState, AuxData]:
        vstep = jax.vmap(member_step, in_axes=(0, 0, 0, 0, None))
        params, opt_state, aux, norms = vstep(
            state.params, state.buffers, state.opt_state, state.lrs, batch)
        if sentinel and state.live is not None:
            gn, un = norms
            finite = _sentinel_finite(aux.losses["loss"], gn, un)
            ok = state.live & finite
            params = _select_members(ok, params, state.params)
            opt_state = _select_members(ok, opt_state, state.opt_state)
            aux = _stamp_inputs_finite(
                aux.replace(finite=finite, grad_norm=gn), batch, True)
        new_state = state.replace(params=params, opt_state=opt_state,
                                  step=state.step + 1)
        return new_state, aux

    return jax.jit(step, donate_argnums=(0,) if donate else ())


class Ensemble:
    """One vmapped bucket of N same-shape members.

    Construction mirrors `FunctionalEnsemble(models, sig, optimizer)`
    (reference: ensemble.py:68-99): takes a list of (params, buffers) pairs
    from `sig.init`, stacks them, and builds the jitted vmapped step.
    """

    def __init__(
        self,
        members: Sequence[tuple[Pytree, Pytree]],
        sig: Any,
        lr: float | Sequence[float] = 1e-3,
        adam_b1: float = 0.9,
        adam_b2: float = 0.999,
        adam_eps: float = 1e-8,
        mesh: Optional[Mesh] = None,
        donate: bool = True,
        use_fused: str | bool = "auto",
        fused_interpret: bool = False,
        fused_batch_tile: Optional[int] = None,
        fused_feat_tile: Optional[int] = None,
        fused_compute_dtype: str = "float32",
        fused_path: Optional[str] = None,
        fused_moments_dtype: str = "float32",
        sentinel: bool = True,
    ):
        if fused_path not in (None, *KERNEL_PATHS):
            raise ValueError(
                f"fused_path must be None or one of {KERNEL_PATHS}, got "
                f"{fused_path!r}")
        if fused_moments_dtype not in ("float32", "bfloat16"):
            raise ValueError(
                f"fused_moments_dtype must be 'float32' or 'bfloat16', got "
                f"{fused_moments_dtype!r}")
        if (fused_moments_dtype != "float32"
                and fused_path not in ("train_step", "train_step_tiled")):
            raise ValueError(
                "fused_moments_dtype='bfloat16' requires "
                "fused_path='train_step' or 'train_step_tiled': only the "
                "whole-step kernels carry "
                "moments through VMEM (the win is their halved HBM traffic),"
                " and an auto-mode path flip would silently change the "
                "optimizer-state dtype mid-run. It is an opt-in DEVIATION "
                "from exact optax/torchopt parity (~8-bit moment mantissas; "
                "update math stays f32).")
        if fused_path is not None and use_fused is False:
            raise ValueError("fused_path requires use_fused=True or 'auto'")
        if not members:
            raise ValueError("ensemble needs at least one member")
        self.sig = sig
        self.sig_name = getattr(sig, "signature_name", sig.__name__)
        self.optimizer = adam_optimizer(adam_b1, adam_b2, adam_eps)
        self._adam_hypers = (adam_b1, adam_b2, adam_eps)
        self.mesh = mesh

        split = [split_buffers(b) for _, b in members]
        statics0 = split[0][1]
        for _, statics in split[1:]:
            if statics != statics0:
                raise ValueError(
                    "members with differing static buffers cannot share a vmapped "
                    f"bucket (got {dict(statics)} vs {dict(statics0)}); use "
                    "EnsembleGroup.build to bucket them")

        params = stack_trees([p for p, _ in members])
        buffers = stack_trees([arrays for arrays, _ in split]) if split[0][0] else {}
        n = len(members)
        lrs = jnp.full((n,), lr, jnp.float32) if isinstance(lr, (int, float)) else jnp.asarray(lr, jnp.float32)
        if lrs.shape != (n,):
            raise ValueError(f"lr must be scalar or length-{n}, got shape {lrs.shape}")
        opt_state = jax.vmap(self.optimizer.init)(params)
        if fused_moments_dtype == "bfloat16":
            # half-width storage for the dictionary-weight moment leaves
            # only — selected BY NAME (encoder/decoder, mirroring the
            # name-based row_params contract) rather than by ndim, so a
            # future 3-d non-dictionary leaf can't be swept in silently;
            # bias moments stay f32 (negligible traffic, less deviation)
            from jax.tree_util import DictKey, tree_map_with_path

            def _is_weight_leaf(path) -> bool:
                return any(isinstance(k, DictKey)
                           and k.key in ("encoder", "decoder")
                           for k in path)

            cast = lambda tree: tree_map_with_path(
                lambda p, a: a.astype(jnp.bfloat16) if _is_weight_leaf(p)
                else a, tree)
            opt_state = opt_state._replace(mu=cast(opt_state.mu),
                                           nu=cast(opt_state.nu))
        self._moments_itemsize = 2 if fused_moments_dtype == "bfloat16" else 4

        # in-graph anomaly sentinel (docs/ARCHITECTURE.md §16): detection +
        # per-member freeze woven into every step program. The opt-out is
        # the bench A/B knob (guardian_soak measures the sentinel's step
        # overhead against it) and the escape hatch should a shape ever
        # regress — live stays in the state either way, so checkpoints
        # keep one format.
        self.sentinel = bool(sentinel)
        self.state = EnsembleState(
            params=params, buffers=buffers, opt_state=opt_state, lrs=lrs,
            step=jnp.zeros((), jnp.int32), live=jnp.ones((n,), jnp.bool_),
            static_buffers=statics0, sig_name=self.sig_name,
        )
        if mesh is not None:
            self.state = shard_ensemble_state(self.state, mesh)

        self._standard_step = make_train_step(sig, self.optimizer,
                                              statics=statics0, donate=donate,
                                              sentinel=self.sentinel)
        self._fused_step = None
        # pick the fused family for this signature, if any: tied_sae (one
        # weight matrix resident per member) or plain sae (two). The
        # eligibility scan costs per-member host syncs — skip it entirely
        # when the fused path was not requested.
        self._fused_n_mats = 1
        self._fused_family: Optional[str] = None
        builders = None
        if use_fused is not False:
            if can_use_fused_tied_step(sig, members, interpret=fused_interpret):
                builders = (make_fused_tied_step, make_fused_tied_step_sharded)
                self._fused_family = ("masked_tied"
                                      if self.sig_name == "masked_tied_sae"
                                      else "tied")
            elif can_use_fused_untied_step(sig, members,
                                           interpret=fused_interpret):
                builders = (make_fused_untied_step,
                            make_fused_untied_step_sharded)
                self._fused_n_mats = 2
                self._fused_family = "untied"
        if use_fused is True and builders is None:
            # explicit request: fail fast with a clear message if ineligible
            raise ValueError(
                "use_fused=True requires a TPU backend (or "
                "fused_interpret=True) and either an identity-centered "
                "tied_sae bucket with zero bias_decay or a plain sae bucket")
        self._fullfused_step = None
        if builders is not None and (use_fused is True or use_fused == "auto"):
            make_single, make_sharded = builders
            self._fused_step = (
                make_sharded(self.optimizer, mesh, donate=donate,
                             interpret=fused_interpret,
                             batch_tile=fused_batch_tile,
                             compute_dtype=fused_compute_dtype,
                             sentinel=self.sentinel)
                if mesh is not None else
                make_single(self.optimizer, donate=donate,
                            interpret=fused_interpret,
                            batch_tile=fused_batch_tile,
                            compute_dtype=fused_compute_dtype,
                            sentinel=self.sentinel))
            # single-device whole-step paths, resolved per batch in
            # _resolve_step and preferred in auto mode when their working
            # sets admit (r4 on-chip A/B: ~9% faster than two_stage):
            # tied = one kernel (grads + VJP + Adam in one Pallas pass;
            # the masked family has no train-step kernel — its coef_mask
            # operand is two-stage only); untied = grads kernel + the
            # feature-tiled Adam/VJP epilogue kernel (a single kernel would
            # exceed VMEM — see make_fullfused_untied_step). Mesh buckets
            # get their whole-step programs lazily from _step_for_plan
            # (make_fullfused_step_sharded: grads kernel → psum("data") →
            # epilogue kernel — ISSUE 15)
            make_fullfused = None
            if mesh is None:
                if (make_single is make_fused_tied_step
                        and self.sig_name == "tied_sae"):
                    make_fullfused = make_fullfused_tied_step
                elif make_single is make_fused_untied_step:
                    make_fullfused = make_fullfused_untied_step
            if make_fullfused is not None:
                self._fullfused_step = make_fullfused(
                    self._adam_hypers, donate=donate,
                    interpret=fused_interpret, batch_tile=fused_batch_tile,
                    compute_dtype=fused_compute_dtype,
                    sentinel=self.sentinel)
        # which fused program actually runs is resolved PER BATCH SHAPE by
        # the roofline admission model (ops/roofline.py, _resolve_step):
        # among the VMEM-admissible candidates — the untiled kernels, the
        # feature-axis-tiled kernels (ops/fused_sae_tiled.py, the path the
        # canonical ratio-16/96 shapes land on), and the whole-step
        # variants of each — the lowest modeled bytes/flops step time
        # wins. fused_path records the resolved choice (a KERNEL_PATHS
        # label | None) for bench/tune labeling and the
        # ensemble.path_resolved obs counter; the fused_path CONSTRUCTOR
        # arg pins it (the bench/tune A/B knob — a perf-regressing
        # default must stay measurable).
        self._forced_fused_path = fused_path
        if fused_path == "train_step" and mesh is None \
                and self._fullfused_step is None:
            raise ValueError(
                "fused_path='train_step' requires a bucket with the fused "
                "path enabled: identity-centered tied_sae (one-kernel whole "
                "step) or plain sae (grads + fused Adam/VJP epilogue)")
        if fused_path in ("two_stage", "two_stage_tiled") and \
                self._fused_step is None:
            raise ValueError(
                f"fused_path={fused_path!r} but no fused kernel is eligible "
                "for this bucket (see use_fused=True error for the "
                "conditions)")
        if fused_path in ("train_step", "train_step_tiled"):
            # whole-step paths exist on meshes too (ISSUE 15): the sharded
            # variant runs grads kernel → psum("data") → Adam/VJP epilogue
            # kernel, so only the masked family (two-stage-only kernels)
            # is excluded
            if self._fused_family not in ("tied", "untied"):
                raise ValueError(
                    f"fused_path={fused_path!r} requires an eligible "
                    "identity-centered tied_sae or plain sae bucket (the "
                    "masked family rides the two-stage kernels only)")
        self.fused = self._fused_step is not None
        self.fused_path = None
        self.fused_plan = None  # the resolved roofline.KernelPlan
        self._fused_explicit = use_fused is True
        self._fused_disabled = use_fused is False
        self._fused_batch_tile = fused_batch_tile
        self._fused_feat_tile = fused_feat_tile
        self._fused_interpret = fused_interpret
        self._fused_compute_dtype = fused_compute_dtype
        # same derivation fused_tied_sae_loss_and_grads uses for its own
        # tile pick, so resolution and kernel admission can never disagree
        self._fused_compute_itemsize = jnp.dtype(fused_compute_dtype).itemsize
        # tiled step programs are built per resolved (path, tiles) and
        # cached — a sweep alternating two batch sizes must not recompile
        self._tiled_steps: dict = {}
        self._step_fn = self._standard_step
        self._scan_fn = None
        self._resolved_batch: Optional[tuple[int, int]] = None
        self._donate = donate

    @property
    def n_members(self) -> int:
        return self.state.n_members

    def freeze_members(self, indices: Sequence[int]) -> None:
        """Clear live-mask bits (host-side; the guardian's per-member
        quarantine, train/guardian.py). Idempotent. A frozen member's
        params and optimizer state pass through every subsequent step
        bit-identically unchanged; live members are untouched."""
        indices = [int(i) for i in indices]
        if not indices or self.state.live is None:
            return
        live = self.state.live.at[jnp.asarray(indices, jnp.int32)].set(False)
        self.state = self.state.replace(live=live)

    def live_mask(self) -> "np.ndarray":
        """Host copy of the [N] live-mask (all-True when the state
        predates the sentinel)."""
        import numpy as np

        if self.state.live is None:
            return np.ones((self.n_members,), np.bool_)
        return np.asarray(jax.device_get(self.state.live))

    def _count_resolution(self, path_label: str, reason: str) -> None:
        """The silent-fallback fix (ISSUE 11): every path resolution is a
        counted, reported event — ``ensemble.path_resolved{path=,reason=}``
        through the obs registry, surfaced by obs.report's "kernel paths"
        section — so a sweep that quietly ran autodiff is visible in every
        run report instead of invisible in all artifacts."""
        from sparse_coding_tpu import obs

        obs.counter("ensemble.path_resolved", path=path_label,
                    reason=reason).inc()

    def _step_for_plan(self, plan):
        """The jitted step program for a resolved KernelPlan. Untiled
        single-device paths reuse the construction-time programs; tiled
        and mesh whole-step programs are built per
        (path, batch_tile, feat_tile) and cached."""
        if plan.path == "train_step" and self.mesh is None:
            return self._fullfused_step
        if plan.path == "two_stage":
            return self._fused_step
        key = (plan.path, plan.batch_tile, plan.feat_tile)
        fn = self._tiled_steps.get(key)
        if fn is None:
            if self.mesh is not None and plan.path in ("train_step",
                                                       "train_step_tiled"):
                # mesh whole-step (ISSUE 15): grads kernel on the local
                # slice → psum("data") → fused Adam/VJP epilogue kernel
                fn = make_fullfused_step_sharded(
                    self._fused_family, self._adam_hypers, self.mesh,
                    tiled=plan.path == "train_step_tiled",
                    batch_tile=plan.batch_tile, feat_tile=plan.feat_tile,
                    donate=self._donate, interpret=self._fused_interpret,
                    compute_dtype=self._fused_compute_dtype,
                    sentinel=self.sentinel)
            elif plan.path == "two_stage_tiled":
                fn = make_tiled_step(
                    self._fused_family, self.optimizer, plan.batch_tile,
                    plan.feat_tile, mesh=self.mesh, donate=self._donate,
                    interpret=self._fused_interpret,
                    compute_dtype=self._fused_compute_dtype,
                    sentinel=self.sentinel)
            else:  # train_step_tiled, single device
                fn = make_fullfused_tiled_step(
                    self._fused_family, self._adam_hypers, plan.batch_tile,
                    plan.feat_tile, donate=self._donate,
                    interpret=self._fused_interpret,
                    compute_dtype=self._fused_compute_dtype,
                    sentinel=self.sentinel)
            self._tiled_steps[key] = fn
        return fn

    def _resolve_step(self, batch_size: int, batch_itemsize: int = 4):
        """Roofline-driven admission (ISSUE 11, ops/roofline.py): for this
        PER-DEVICE batch slice, rank every VMEM-admissible kernel path —
        untiled two-stage/whole-step, feature-axis-tiled two-stage/whole-
        step — by modeled HBM-bytes/MXU-flops step time and pick the
        winner's (path, batch_tile, feat_tile); autodiff only when NO
        fused tile admits (e.g. a batch no candidate divides), and then
        as a counted ``ensemble.path_resolved`` event, never a silent
        flip. `batch_itemsize` must be the itemsize the KERNEL will see
        (2 only for bf16, see kernel_batch_itemsize) so this check and
        the kernels' own tile picks always agree. Re-resolved whenever
        the incoming batch size/dtype changes; the scanned-step cache is
        invalidated when the program flips."""
        if (batch_size, batch_itemsize) == self._resolved_batch:
            return
        prev_fn = self._step_fn
        plan = None
        local = n_feats = d = None
        if self._fused_step is not None:
            from sparse_coding_tpu.ops import roofline

            n_feats = self.state.params["encoder"].shape[1]
            d = self.state.params["encoder"].shape[2]
            local = (batch_size // self.mesh.shape["data"]
                     if self.mesh is not None else batch_size)
            plan = roofline.choose_plan(
                n_members=self.n_members, batch=local, n_feats=n_feats,
                d=d, family=self._fused_family,
                sharded=self.mesh is not None,
                batch_itemsize=batch_itemsize,
                compute_itemsize=self._fused_compute_itemsize,
                moments_itemsize=self._moments_itemsize,
                forced_path=self._forced_fused_path,
                batch_tile=self._fused_batch_tile,
                feat_tile=self._fused_feat_tile,
                sentinel=self.sentinel,
                # interpret-mode buckets (CPU drills) admit feature tiles
                # Mosaic's lane rule would reject on real TPU — mirror
                # prepare_tiled_batch so resolution and kernel admission
                # can never disagree
                lane_rule=not self._fused_interpret)
        force = self._forced_fused_path
        if (plan is None or plan.path is None) and force is not None:
            kind = {"train_step": "train-step tile",
                    "two_stage": "batch tile"}.get(
                        force, "(batch, feature) tile pair")
            raise ValueError(
                f"fused_path={force!r} but no VMEM-fitting {kind} exists "
                f"for per-device batch={local}, n_feats={n_feats}, d={d}")
        if plan is not None and plan.path is not None:
            self._step_fn = self._step_for_plan(plan)
            self.fused = True
            self.fused_path = plan.path
            self.fused_plan = plan
            self._count_resolution(plan.path, plan.reason)
        elif self._fused_explicit:
            raise ValueError(
                f"use_fused=True but no VMEM-fitting batch tile exists for "
                f"per-device batch={local}, n_feats={n_feats}, d={d}; choose "
                "a batch size divisible by 64/128/256/512 or drop use_fused")
        else:
            self._step_fn = self._standard_step
            self.fused = False  # auto mode: keep autodiff — COUNTED
            self.fused_path = None
            self.fused_plan = plan
            reason = (plan.reason if plan is not None else
                      "fused_disabled" if self._fused_disabled else
                      "family_ineligible")
            self._count_resolution("autodiff", reason)
        if self._step_fn is not prev_fn:
            self._scan_fn = None
        self._resolved_batch = (batch_size, batch_itemsize)

    def step_batch(self, batch: Array) -> AuxData:
        """One training step on a [batch, d] activation slab shared by every
        member (reference: ensemble.py:175-193). Returns stacked per-member aux."""
        if self.mesh is not None:
            n_data = self.mesh.shape["data"]
            if batch.shape[0] % n_data != 0:
                raise ValueError(
                    f"batch size {batch.shape[0]} not divisible by mesh data "
                    f"axis {n_data}; drop the remainder or pad the batch")
        from sparse_coding_tpu.ops.fused_sae import kernel_batch_itemsize

        self._resolve_step(batch.shape[0], kernel_batch_itemsize(batch.dtype))
        if self.mesh is not None:
            batch = partition.place_batch(batch, self.mesh)
        self.state, aux = self._step_fn(self.state, batch)
        return aux

    def run_steps(self, batches: Array) -> AuxData:
        """K training steps in ONE device program via lax.scan over a
        [K, B, d] batch stack — no per-step Python dispatch (useful when the
        step is fast enough that host overhead would bottleneck, e.g. the
        bench loop). Returns aux stacked on a leading K axis."""
        if self.mesh is not None:
            n_data = self.mesh.shape["data"]
            if batches.shape[1] % n_data != 0:
                raise ValueError(
                    f"batch size {batches.shape[1]} not divisible by mesh "
                    f"data axis {n_data}")
        from sparse_coding_tpu.ops.fused_sae import kernel_batch_itemsize

        self._resolve_step(int(batches.shape[1]),
                           kernel_batch_itemsize(batches.dtype))
        if self.mesh is not None:
            batches = partition.place_batch(batches, self.mesh, stacked=True)
        if self._scan_fn is None:
            self._scan_fn = self._build_scan_fn()
        self.state, aux = self._scan_fn(self.state, batches)
        return aux

    def _build_scan_fn(self):
        """The jitted K-step scan program over the CURRENTLY-resolved
        step (single home — run_steps and precompile must build the
        exact same program or the warm-start would warm a stranger)."""
        step_fn = self._step_fn  # jitted; inlines under the outer jit

        def run(state, batches):
            return jax.lax.scan(step_fn, state, batches)

        return jax.jit(run, donate_argnums=(0,) if self._donate else ())

    def precompile(self, batch_shape: Sequence[int], dtype=jnp.float32,
                   label: str = "ensemble"):
        """Compile-or-load the exact step program ``step_batch`` (2-d
        shape) or ``run_steps`` (3-d ``[K, B, d]`` shape) will dispatch
        for batches of ``batch_shape``/``dtype``, WITHOUT executing a
        step — training state is untouched. Through
        ``xcache.cached_compile`` (docs/ARCHITECTURE.md §13): with the
        executable cache enabled the program is serialized to disk, the
        sweep's warm-start loads it before the first chunk is read, and
        the jax persistent compilation cache makes the subsequent real
        dispatch's backend compile a disk hit instead of an XLA compile.
        Returns the compiled executable (callers want the side effect)."""
        from sparse_coding_tpu import xcache
        from sparse_coding_tpu.ops.fused_sae import kernel_batch_itemsize

        shape = tuple(int(s) for s in batch_shape)
        if len(shape) not in (2, 3):
            raise ValueError(f"batch_shape must be [B, d] or [K, B, d], "
                             f"got {shape}")
        scan = len(shape) == 3
        dt = jnp.dtype(dtype)
        self._resolve_step(shape[1] if scan else shape[0],
                           kernel_batch_itemsize(dt))
        if scan:
            if self._scan_fn is None:
                self._scan_fn = self._build_scan_fn()
            fn = self._scan_fn
        else:
            fn = self._step_fn
        if self.mesh is not None:
            spec = jax.ShapeDtypeStruct(
                shape, dt,
                sharding=partition.batch_sharding(self.mesh, stacked=scan))
        else:
            spec = jax.ShapeDtypeStruct(shape, dt)
        return xcache.cached_compile(
            fn, (self.state, spec), label=label,
            manifest_desc={"kind": "sweep", "label": label,
                           "sig": self.sig_name,
                           "n_members": int(self.n_members),
                           "shape": list(shape), "dtype": str(dt),
                           "fused_path": self.fused_path})

    def step_cost(self, batch_rows: int) -> "obs.StepCost":
        """The :class:`obs.perf.StepCost` of ONE step at ``batch_rows``
        for the currently-resolved program (ISSUE 12): model flops from
        the SHARED FLOP model (``roofline.model_flops_per_activation`` —
        required flops, so the MFU numerator never depends on which
        kernel executed), prediction + path/tile labels from the resolved
        :class:`~sparse_coding_tpu.ops.roofline.KernelPlan`. Signatures
        without an "encoder" dictionary param return a zero-flops cost
        (the probe then records device walls only)."""
        from sparse_coding_tpu import obs
        from sparse_coding_tpu.ops import roofline

        enc = self.state.params.get("encoder") \
            if isinstance(self.state.params, dict) else None
        if enc is None or enc.ndim != 3:
            return obs.StepCost(path=self.fused_path or "autodiff",
                                activations=int(batch_rows))
        n_feats, d = int(enc.shape[1]), int(enc.shape[2])
        flops = roofline.model_flops_per_activation(
            self.n_members, n_feats, d) * batch_rows
        plan = self.fused_plan
        if plan is None:
            # fused disabled / family ineligible: model the autodiff
            # program so the roofline gap stays populated on this path
            plan = roofline.autodiff_plan(
                self.n_members, batch_rows, n_feats, d,
                n_mats=2 if "decoder" in self.state.params else 1,
                sentinel=self.sentinel, reason="unresolved")
        tile = ""
        if plan.batch_tile or plan.feat_tile:
            tile = f"{plan.batch_tile or '-'}x{plan.feat_tile or '-'}"
        return obs.StepCost(flops=flops,
                            path=self.fused_path or "autodiff",
                            predicted_s=float(plan.est_s),
                            hbm_bytes=float(plan.hbm_bytes), tile=tile,
                            activations=int(batch_rows))

    def unstack(self) -> list[tuple[Pytree, dict]]:
        """Per-member (params, buffers incl. statics), host-side
        (reference: ensemble.py:59-66 unstack_dict)."""
        params = jax.device_get(self.state.params)
        buffers = jax.device_get(self.state.buffers)
        out = []
        for i in range(self.n_members):
            member_buffers = merge_buffers(
                tree_index(buffers, i) if buffers else {}, self.state.static_buffers)
            out.append((tree_index(params, i), member_buffers))
        return out

    def to_learned_dicts(self) -> list:
        """Export every member as an inference LearnedDict
        (reference: big_sweep.py:202-225 `unstacked_to_learned_dicts`)."""
        return [self.sig.to_learned_dict(p, b) for p, b in self.unstack()]


# Per-feature param contract for resurrection: which TOP-LEVEL param names
# are dictionary rows (refreshed with new directions) and which are
# per-feature scalars (reset when dead). Name-based on purpose — shape-based
# guessing collides (a learnable center [N, d] equals [N, n_feats] whenever
# the dict ratio is 1). Covers the built-in zoo: encoder/decoder (SAEs),
# weights (RICA), enc1_w (semilinear second encoder layer). Signatures with
# other per-feature params pass their own row_params / scalar_defaults.
_RESURRECT_ROW_PARAMS = ("encoder", "decoder", "weights", "enc1_w")
_RESURRECT_SCALAR_DEFAULTS = {
    "encoder_bias": 0.0,
    "enc1_b": 0.0,
    "activation_scale": 1.0,  # thresholding gate (models/sae.py init)
    "activation_gain": 0.0,
}
# signatures whose per-feature scalar init is a nonzero constant
_SIG_SCALAR_OVERRIDES = {
    "positive_tied_sae": {"encoder_bias": -1.0},  # models/positive.py init
}


def resurrect_ensemble_features(
        state: EnsembleState, dead_mask: Array, key: Array,
        row_params=None, scalar_defaults=None) -> EnsembleState:
    """Reinitialize dead features across ALL ensemble members in one vmapped
    pass: dead dictionary rows get fresh random unit directions scaled to the
    member's mean LIVE-row norm, per-feature scalars reset (to the
    signature's constant init where known, 0 otherwise), and their Adam
    moments zeroed. Generalizes the reference's single-model resurrection
    (huge_batch_size.py:224-250) to the vmapped ensemble; track deadness by
    accumulating `aux.feat_activity` between calls.

    Only named top-level params are touched — nested pytrees (LISTA's
    encoder_layers) and non-per-feature params (learnable centers) are left
    alone by design. `row_params` / `scalar_defaults` accept any iterable /
    mapping and extend the built-in contract. dead_mask: [N, n_feats] bool."""
    rows = tuple(row_params) if row_params is not None else _RESURRECT_ROW_PARAMS
    defaults = dict(_RESURRECT_SCALAR_DEFAULTS)
    defaults.update(_SIG_SCALAR_OVERRIDES.get(state.sig_name, {}))
    if scalar_defaults is not None:
        defaults.update(dict(scalar_defaults))
    return _resurrect_jit(state, dead_mask, key, rows,
                          tuple(sorted(defaults.items())))


@functools.partial(jax.jit, static_argnames=("row_params", "scalar_defaults"))
def _resurrect_jit(state: EnsembleState, dead_mask: Array, key: Array,
                   row_params: tuple, scalar_defaults: tuple) -> EnsembleState:
    params = dict(state.params)
    n_members, n_feats = dead_mask.shape
    defaults = dict(scalar_defaults)

    def refresh_rows(w, sub_key):  # w: [N, n, d]
        fresh = jax.random.normal(sub_key, w.shape, w.dtype)
        fresh = fresh / jnp.linalg.norm(fresh, axis=-1, keepdims=True)
        # scale to the member's mean LIVE-row norm: including dead rows would
        # shrink reinits progressively across resurrection cycles
        norms = jnp.linalg.norm(w, axis=-1)  # [N, n]
        live = ~dead_mask
        live_count = jnp.maximum(jnp.sum(live, axis=-1), 1)
        scale = jnp.sum(norms * live, axis=-1) / live_count  # [N]
        fresh = fresh * scale[:, None, None]
        return jnp.where(dead_mask[..., None], fresh, w)

    keys = iter(jax.random.split(key, len(row_params)))
    for name in row_params:
        if name in params:
            params[name] = refresh_rows(params[name], next(keys))
    for name, default in defaults.items():
        if name in params:
            params[name] = jnp.where(dead_mask, default, params[name])

    touched = set(row_params) | set(defaults)

    def reset_moment(tree):
        def reset(name, m):
            if name not in touched or not hasattr(m, "ndim"):
                return m
            if name in row_params:
                return jnp.where(dead_mask[..., None], 0.0, m)
            return jnp.where(dead_mask, 0.0, m)
        return {k: reset(k, v) for k, v in tree.items()}

    opt_state = state.opt_state._replace(mu=reset_moment(state.opt_state.mu),
                                         nu=reset_moment(state.opt_state.nu))
    return state.replace(params=params, opt_state=opt_state)


def shard_ensemble_state(state: EnsembleState, mesh: Mesh) -> EnsembleState:
    """Place a stacked state on a mesh through the partition rule layer
    (parallel/partition.py ENSEMBLE_STATE_RULES, §19): ensemble axis over
    "model" (each model-shard owns N/mesh_model members, the analogue of
    one reference worker process, cluster_runs.py:110-127), scalars
    replicated, one ``partition.place`` fault-sited device_put."""
    n_model = mesh.shape["model"]
    if state.n_members % n_model != 0:
        raise ValueError(
            f"ensemble size {state.n_members} not divisible by mesh model axis "
            f"{n_model}; pad the sweep grid or choose a dividing mesh_model")
    return partition.place_tree(state, mesh,
                                partition.ENSEMBLE_STATE_RULES)


class EnsembleGroup:
    """A set of buckets trained together on the same data stream — the
    analogue of the reference's `no_stacking` mode (ensemble.py:100-116) and
    of running several `FunctionalEnsemble`s per sweep (big_sweep.py:331-336).

    Buckets are keyed by static buffers; each bucket is its own jitted vmapped
    step, so e.g. TopK members with k=4,8,16 form three buckets that still
    pipeline on device (dispatch is async)."""

    def __init__(self, ensembles: dict[str, Ensemble]):
        self.ensembles = dict(ensembles)

    @classmethod
    def build(
        cls,
        sig: Any,
        member_inits: Sequence[tuple[Pytree, Pytree]],
        lr: float = 1e-3,
        mesh: Optional[Mesh] = None,
        **adam_kwargs,
    ) -> "EnsembleGroup":
        """Bucket members by static buffers and build one Ensemble per bucket."""
        buckets: dict[StaticBuffers, list[tuple[Pytree, Pytree]]] = {}
        for member in member_inits:
            _, statics = split_buffers(member[1])
            buckets.setdefault(statics, []).append(member)
        ensembles = {}
        for statics, members in buckets.items():
            name = getattr(sig, "signature_name", sig.__name__) + (
                "_" + "_".join(f"{k}{v}" for k, v in statics) if statics else "")
            ensembles[name] = Ensemble(members, sig, lr=lr, mesh=mesh, **adam_kwargs)
        return cls(ensembles)

    def step_batch(self, batch: Array) -> dict[str, AuxData]:
        return {name: ens.step_batch(batch) for name, ens in self.ensembles.items()}

    def run_steps(self, batches: Array) -> dict[str, AuxData]:
        """K scanned steps per bucket on one [K, B, d] batch stack (see
        Ensemble.run_steps); buckets still pipeline on device."""
        return {name: ens.run_steps(batches)
                for name, ens in self.ensembles.items()}

    def step_cost(self, batch_rows: int):
        """Aggregate :class:`obs.perf.StepCost` across buckets (mixed
        paths label ``mixed``; see obs.perf.combine_costs)."""
        from sparse_coding_tpu import obs

        return obs.combine_costs([ens.step_cost(batch_rows)
                                  for ens in self.ensembles.values()])

    def to_learned_dicts(self) -> dict[str, list]:
        return {name: ens.to_learned_dicts() for name, ens in self.ensembles.items()}
