"""Typed failure taxonomy for the resilience layer.

Every hardened subsystem converts low-level failures into one of these
types at its boundary, so callers can distinguish "data is damaged"
(corruption — do NOT retry, fall back or quarantine) from "the operation
hiccupped" (transient I/O — bounded retry, resilience/retry.py) from "we
were asked to stop" (preemption — checkpoint and exit cleanly,
resilience/preempt.py).
"""

from __future__ import annotations

from pathlib import Path


class ResilienceError(RuntimeError):
    """Base class for typed resilience-layer failures."""


class UnknownFaultSiteError(ResilienceError, ValueError):
    """A fault/crash plan named a site no module registered. Raised eagerly
    at plan parse (env or code) — a typo in ``SPARSE_CODING_FAULT_PLAN`` /
    ``SPARSE_CODING_CRASH_PLAN`` would otherwise disable the injection
    without warning, and an untested fault plan is worse than none.
    Subclasses ValueError so pre-existing ``except ValueError`` callers and
    tests keep working."""

    def __init__(self, site: str, registered, kind: str = "fault"):
        super().__init__(
            f"unknown {kind} site {site!r} (registered: {sorted(registered)})")
        self.site = site
        self.kind = kind


class ChunkCorruptionError(ResilienceError):
    """A chunk file's content does not match the digest recorded in
    meta.json at finalize (or the file is structurally unreadable).
    Names the chunk index so operators can delete/re-harvest exactly one
    chunk; ``ChunkStore(quarantine_corrupt=True)`` readers skip it."""

    def __init__(self, chunk_index: int, path: str | Path, reason: str):
        super().__init__(
            f"chunk {chunk_index} corrupt at {path}: {reason}")
        self.chunk_index = int(chunk_index)
        self.path = Path(path)
        self.reason = reason


class CheckpointCorruptionError(ResilienceError):
    """A checkpoint payload fails its digest manifest (or cannot be
    deserialized). ``train/sweep.py::resume_sweep_state`` reacts by
    falling back to the ``ckpt_prev/`` last-good set."""

    def __init__(self, path: str | Path, reason: str):
        super().__init__(f"checkpoint corrupt at {path}: {reason}")
        self.path = Path(path)
        self.reason = reason


class LedgerCorruptionError(ResilienceError):
    """A small JSON ledger (``guardian.json``, ``quarantine.json``) fails
    its embedded payload digest (resilience/manifest.py
    ``check_payload_digest``). Atomic writes make torn ledgers impossible,
    so a mismatch means bit rot or a hand-edit that forgot to re-digest —
    either way the recorded incidents can no longer be trusted and the
    reader must not silently act on them. ``fsck`` reports the same
    condition as an ``INCONSISTENT`` finding."""

    def __init__(self, path: str | Path, reason: str):
        super().__init__(f"ledger corrupt at {path}: {reason}")
        self.path = Path(path)
        self.reason = reason


class UndersizedInputError(ResilienceError, ValueError):
    """A streaming statistic consumed ZERO complete batches (input smaller
    than ``batch_size``) — the result would be silent NaN, which is exactly
    the failure class the training guardian exists to keep out of sweeps
    (docs/ARCHITECTURE.md §16; ADVICE r5 #4). Subclasses ValueError so
    pre-existing ``except ValueError`` callers keep working."""

    def __init__(self, reason: str):
        super().__init__(reason)


class DivergenceHaltError(ResilienceError):
    """The training guardian exhausted its rollback ladder: a rollback
    was demanded again at a site that already rolled back (or past the
    run's rollback budget), so the incident is structural, not transient
    (train/guardian.py, docs/ARCHITECTURE.md §16). ``diagnosis`` is the
    operator's triage fork:

    - ``"poisoned-data"`` — non-finite activations keep reaching the step
      (the chunk quarantine did not stick, or the rot is store-wide);
      re-harvest / scrub the store before re-running.
    - ``"hyperparameter"`` — members keep diverging on inputs the sentinel
      proved finite; shrink the lr/l1 corners of the grid.
    """

    def __init__(self, site: str, diagnosis: str, detail: str = ""):
        super().__init__(
            f"sweep halted by the guardian at {site}: {diagnosis}"
            + (f" ({detail})" if detail else ""))
        self.site = site
        self.diagnosis = diagnosis
        self.detail = detail
