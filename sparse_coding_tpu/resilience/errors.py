"""Typed failure taxonomy for the resilience layer.

Every hardened subsystem converts low-level failures into one of these
types at its boundary, so callers can distinguish "data is damaged"
(corruption — do NOT retry, fall back or quarantine) from "the operation
hiccupped" (transient I/O — bounded retry, resilience/retry.py) from "we
were asked to stop" (preemption — checkpoint and exit cleanly,
resilience/preempt.py).
"""

from __future__ import annotations

from pathlib import Path


class ResilienceError(RuntimeError):
    """Base class for typed resilience-layer failures."""


class UnknownFaultSiteError(ResilienceError, ValueError):
    """A fault/crash plan named a site no module registered. Raised eagerly
    at plan parse (env or code) — a typo in ``SPARSE_CODING_FAULT_PLAN`` /
    ``SPARSE_CODING_CRASH_PLAN`` would otherwise disable the injection
    without warning, and an untested fault plan is worse than none.
    Subclasses ValueError so pre-existing ``except ValueError`` callers and
    tests keep working."""

    def __init__(self, site: str, registered, kind: str = "fault"):
        super().__init__(
            f"unknown {kind} site {site!r} (registered: {sorted(registered)})")
        self.site = site
        self.kind = kind


class ChunkCorruptionError(ResilienceError):
    """A chunk file's content does not match the digest recorded in
    meta.json at finalize (or the file is structurally unreadable).
    Names the chunk index so operators can delete/re-harvest exactly one
    chunk; ``ChunkStore(quarantine_corrupt=True)`` readers skip it."""

    def __init__(self, chunk_index: int, path: str | Path, reason: str):
        super().__init__(
            f"chunk {chunk_index} corrupt at {path}: {reason}")
        self.chunk_index = int(chunk_index)
        self.path = Path(path)
        self.reason = reason


class CheckpointCorruptionError(ResilienceError):
    """A checkpoint payload fails its digest manifest (or cannot be
    deserialized). ``train/sweep.py::resume_sweep_state`` reacts by
    falling back to the ``ckpt_prev/`` last-good set."""

    def __init__(self, path: str | Path, reason: str):
        super().__init__(f"checkpoint corrupt at {path}: {reason}")
        self.path = Path(path)
        self.reason = reason
