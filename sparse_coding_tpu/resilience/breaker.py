"""Circuit breaker for the serving dispatch path.

Classic three-state machine, host-side Python only (the serving metrics
doctrine: instrumentation must never touch jax):

- **closed**: all traffic flows; ``failure_threshold`` CONSECUTIVE
  dispatch failures open the circuit.
- **open**: every dispatch (and, via the engine's admission check, every
  submit) fails fast with a typed error instead of queueing work a sick
  backend cannot serve — bounded load shedding, no wedged queue.
- **half_open**: after ``reset_timeout_s`` one probe dispatch is let
  through; success closes the circuit, failure re-opens it (and restarts
  the cooldown). Only one probe is ever in flight.

**Probe tokens.** Dispatches are concurrent, so an outcome recorded
during HALF_OPEN is not necessarily the probe's: a dispatch admitted
while the circuit was still CLOSED can finish *after* the circuit opened
and cooled down, and its stale success must not close the circuit (nor
its stale failure consume the probe). ``allow()`` therefore hands the
caller a token — ``True`` for ordinary closed-state admissions, a unique
:class:`ProbeToken` when it admits THE probe — and the caller passes that
token back to ``record_success``/``record_failure``. While HALF_OPEN,
only the current probe token's outcome transitions the state machine;
token-less (or stale-token) outcomes still update the failure counter but
cannot close the circuit or free the probe slot.

The clock is injectable so tests drive the cooldown deterministically;
``on_transition`` lets the engine mirror every state change into
``serve/metrics.py`` snapshots.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Optional, Union

TRANSITION_HISTORY = 256  # bounded: a flapping breaker must not grow RAM

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


class ProbeToken:
    """Opaque truthy handle for the single HALF_OPEN probe. Identity is
    the credential: only the outcome reported with the CURRENT token
    moves the state machine out of HALF_OPEN."""

    __slots__ = ()


class CircuitBreaker:
    def __init__(self, failure_threshold: int = 5,
                 reset_timeout_s: float = 30.0,
                 clock: Callable[[], float] = time.monotonic,
                 on_transition: Optional[Callable[[str, str], None]] = None):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self._threshold = int(failure_threshold)
        self._reset_s = float(reset_timeout_s)
        self._clock = clock
        self._on_transition = on_transition
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive_failures = 0
        self._opened_at: Optional[float] = None
        self._probe_token: Optional[ProbeToken] = None
        self._transitions: deque[str] = deque(maxlen=TRANSITION_HISTORY)
        self._n_transitions = 0

    def set_on_transition(self,
                          cb: Optional[Callable[[str, str], None]]) -> None:
        """Attach/replace the transition mirror (the serving engine wires
        this to ServingMetrics.record_breaker_transition)."""
        with self._lock:
            self._on_transition = cb

    # -- state machine --------------------------------------------------------

    def _move(self, new: str) -> None:
        # lock held by caller
        old, self._state = self._state, new
        self._transitions.append(f"{old}->{new}")
        self._n_transitions += 1
        if self._on_transition is not None:
            self._on_transition(old, new)

    def allow(self) -> Union[bool, ProbeToken]:
        """May a dispatch proceed right now? Returns a truthy admission
        token: ``True`` in CLOSED, a :class:`ProbeToken` when this call
        admits the single half-open probe (in OPEN past the cooldown this
        moves to HALF_OPEN first), ``False`` otherwise. Pass the returned
        token to ``record_success``/``record_failure`` so a raced
        non-probe outcome can never masquerade as the probe's."""
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                if self._clock() - self._opened_at >= self._reset_s:
                    self._move(HALF_OPEN)
                    self._probe_token = ProbeToken()
                    return self._probe_token
                return False
            # HALF_OPEN: only the single in-flight probe
            if self._probe_token is None:
                self._probe_token = ProbeToken()
                return self._probe_token
            return False

    def admission_allowed(self) -> bool:
        """Non-mutating submit-time check: shed new work only while the
        circuit is OPEN and the cooldown has not elapsed (a probe-eligible
        or half-open circuit still admits, so recovery traffic exists)."""
        with self._lock:
            return not (self._state == OPEN
                        and self._clock() - self._opened_at < self._reset_s)

    def _is_probe(self, token) -> bool:
        # lock held by caller
        return (isinstance(token, ProbeToken)
                and token is self._probe_token)

    def record_success(self, token: Union[bool, ProbeToken, None] = None
                       ) -> None:
        with self._lock:
            self._consecutive_failures = 0
            if self._state == CLOSED:
                return
            # OPEN or HALF_OPEN: only the live probe's success heals —
            # a raced dispatch that was admitted before the circuit
            # opened proves nothing about the backend NOW
            if self._state == HALF_OPEN and self._is_probe(token):
                self._probe_token = None
                self._move(CLOSED)

    def record_failure(self, token: Union[bool, ProbeToken, None] = None
                       ) -> None:
        with self._lock:
            self._consecutive_failures += 1
            if self._state == HALF_OPEN:
                if self._is_probe(token):
                    # the probe itself failed: re-open, restart cooldown
                    self._probe_token = None
                    self._opened_at = self._clock()
                    self._move(OPEN)
                # a raced non-probe failure neither consumes the probe
                # slot nor re-opens: the probe's own outcome decides
            elif (self._state == CLOSED
                    and self._consecutive_failures >= self._threshold):
                self._opened_at = self._clock()
                self._move(OPEN)
            elif self._state == OPEN:
                # failures while open (e.g. a raced dispatch) restart the
                # cooldown — a sick backend gets its full quiet period
                self._opened_at = self._clock()

    # -- read side ------------------------------------------------------------

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def seconds_until_probe(self) -> float:
        with self._lock:
            if self._state != OPEN:
                return 0.0
            return max(0.0, self._reset_s - (self._clock() - self._opened_at))

    def snapshot(self) -> dict:
        with self._lock:
            return {"state": self._state,
                    "consecutive_failures": self._consecutive_failures,
                    "failure_threshold": self._threshold,
                    "reset_timeout_s": self._reset_s,
                    "probe_in_flight": self._probe_token is not None,
                    "n_transitions": self._n_transitions,
                    "transitions": list(self._transitions)}
