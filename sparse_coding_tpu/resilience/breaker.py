"""Circuit breaker for the serving dispatch path.

Classic three-state machine, host-side Python only (the serving metrics
doctrine: instrumentation must never touch jax):

- **closed**: all traffic flows; ``failure_threshold`` CONSECUTIVE
  dispatch failures open the circuit.
- **open**: every dispatch (and, via the engine's admission check, every
  submit) fails fast with a typed error instead of queueing work a sick
  backend cannot serve — bounded load shedding, no wedged queue.
- **half_open**: after ``reset_timeout_s`` one probe dispatch is let
  through; success closes the circuit, failure re-opens it (and restarts
  the cooldown). Only one probe is ever in flight.

The clock is injectable so tests drive the cooldown deterministically;
``on_transition`` lets the engine mirror every state change into
``serve/metrics.py`` snapshots.
"""

from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Optional

TRANSITION_HISTORY = 256  # bounded: a flapping breaker must not grow RAM

CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


class CircuitBreaker:
    def __init__(self, failure_threshold: int = 5,
                 reset_timeout_s: float = 30.0,
                 clock: Callable[[], float] = time.monotonic,
                 on_transition: Optional[Callable[[str, str], None]] = None):
        if failure_threshold < 1:
            raise ValueError("failure_threshold must be >= 1")
        self._threshold = int(failure_threshold)
        self._reset_s = float(reset_timeout_s)
        self._clock = clock
        self._on_transition = on_transition
        self._lock = threading.Lock()
        self._state = CLOSED
        self._consecutive_failures = 0
        self._opened_at: Optional[float] = None
        self._probe_in_flight = False
        self._transitions: deque[str] = deque(maxlen=TRANSITION_HISTORY)
        self._n_transitions = 0

    def set_on_transition(self,
                          cb: Optional[Callable[[str, str], None]]) -> None:
        """Attach/replace the transition mirror (the serving engine wires
        this to ServingMetrics.record_breaker_transition)."""
        with self._lock:
            self._on_transition = cb

    # -- state machine --------------------------------------------------------

    def _move(self, new: str) -> None:
        # lock held by caller
        old, self._state = self._state, new
        self._transitions.append(f"{old}->{new}")
        self._n_transitions += 1
        if self._on_transition is not None:
            self._on_transition(old, new)

    def allow(self) -> bool:
        """May a dispatch proceed right now? In OPEN past the cooldown
        this admits exactly one probe and moves to HALF_OPEN."""
        with self._lock:
            if self._state == CLOSED:
                return True
            if self._state == OPEN:
                if self._clock() - self._opened_at >= self._reset_s:
                    self._move(HALF_OPEN)
                    self._probe_in_flight = True
                    return True
                return False
            # HALF_OPEN: only the single in-flight probe
            if not self._probe_in_flight:
                self._probe_in_flight = True
                return True
            return False

    def admission_allowed(self) -> bool:
        """Non-mutating submit-time check: shed new work only while the
        circuit is OPEN and the cooldown has not elapsed (a probe-eligible
        or half-open circuit still admits, so recovery traffic exists)."""
        with self._lock:
            return not (self._state == OPEN
                        and self._clock() - self._opened_at < self._reset_s)

    def record_success(self) -> None:
        with self._lock:
            self._consecutive_failures = 0
            self._probe_in_flight = False
            if self._state != CLOSED:
                self._move(CLOSED)

    def record_failure(self) -> None:
        with self._lock:
            self._consecutive_failures += 1
            self._probe_in_flight = False
            if self._state == HALF_OPEN or (
                    self._state == CLOSED
                    and self._consecutive_failures >= self._threshold):
                self._opened_at = self._clock()
                self._move(OPEN)
            elif self._state == OPEN:
                # failures while open (e.g. a raced dispatch) restart the
                # cooldown — a sick backend gets its full quiet period
                self._opened_at = self._clock()

    # -- read side ------------------------------------------------------------

    @property
    def state(self) -> str:
        with self._lock:
            return self._state

    def seconds_until_probe(self) -> float:
        with self._lock:
            if self._state != OPEN:
                return 0.0
            return max(0.0, self._reset_s - (self._clock() - self._opened_at))

    def snapshot(self) -> dict:
        with self._lock:
            return {"state": self._state,
                    "consecutive_failures": self._consecutive_failures,
                    "failure_threshold": self._threshold,
                    "reset_timeout_s": self._reset_s,
                    "n_transitions": self._n_transitions,
                    "transitions": list(self._transitions)}
