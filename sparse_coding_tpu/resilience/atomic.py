"""Atomic durable file writes: tmp + fsync + rename.

``os.replace`` on the same filesystem is atomic, so a reader (or a crash)
can only ever observe the old complete file or the new complete file —
never a truncated hybrid. The fsync before the rename makes the CONTENT
durable before the name flips; the directory fsync after makes the rename
itself durable (a power cut between the two otherwise resurrects the old
file, which is still a complete file — the invariant holds either way).
"""

from __future__ import annotations

import os
from pathlib import Path


def fsync_dir(path: str | Path) -> None:
    """Best-effort directory fsync (some filesystems refuse O_RDONLY dir
    fsync; the rename is already atomic, so failure here only weakens
    durability, not consistency)."""
    try:
        fd = os.open(str(path), os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def atomic_write_bytes(path: str | Path, data: bytes,
                       fsync: bool = True) -> None:
    """Write ``data`` to ``path`` such that ``path`` always holds either
    its previous complete content or ``data`` in full.

    ``fsync=False`` keeps the rename atomicity (readers still never see
    a torn file) but skips both fsyncs — for high-frequency BOOKKEEPING
    files whose loss to a power cut is self-healing (e.g. the xcache LRU
    manifest, which reconciles against its directory); data artifacts
    must keep the default."""
    path = Path(path)
    tmp = path.parent / f".{path.name}.tmp.{os.getpid()}"
    try:
        with open(tmp, "wb") as f:
            f.write(data)
            f.flush()
            if fsync:
                os.fsync(f.fileno())
        os.replace(tmp, path)
    except BaseException:
        tmp.unlink(missing_ok=True)
        raise
    if fsync:
        fsync_dir(path.parent)


def atomic_write_text(path: str | Path, text: str,
                      fsync: bool = True) -> None:
    atomic_write_bytes(path, text.encode(), fsync=fsync)


def atomic_save_npy(path: str | Path, arr) -> None:
    """np.save with the tmp+fsync+rename discipline (np.save to the final
    path directly can leave a truncated .npy on crash/ENOSPC)."""
    import io

    import numpy as np

    buf = io.BytesIO()
    np.save(buf, arr)
    atomic_write_bytes(path, buf.getvalue())


def atomic_pickle_dump(path: str | Path, obj) -> None:
    """pickle.dump with the tmp+fsync+rename discipline (a crash mid-dump
    to the final path leaves a truncated pickle another process would
    choke on)."""
    import pickle

    atomic_write_bytes(path, pickle.dumps(obj))
