"""Deterministic fault injection + the hardening it exercises.

The production north star (ROADMAP.md) is a system that runs unattended —
long multi-config sweeps surviving preemption and serving heavy traffic
through partial failure. This package is the failure-handling substrate,
built so that every handler is *driven by injected faults in CI* rather
than assumed:

- :mod:`faults`   — named fault sites + Nth-hit :class:`FaultPlan`s
  (in code via :func:`inject`, or ``SPARSE_CODING_FAULT_PLAN`` env);
- :mod:`errors`   — the typed failure taxonomy (corruption vs transient);
- :mod:`retry`    — bounded retry-with-backoff for transient I/O;
- :mod:`atomic`   — tmp+fsync+rename write discipline;
- :mod:`manifest` — content digests + checkpoint digest manifests;
- :mod:`breaker`  — the serving circuit breaker;
- :mod:`preempt`  — SIGTERM → checkpoint-and-exit for sweeps;
- :mod:`crash`    — named crash barriers: deterministic whole-process
  SIGKILL at the Nth hit (``SPARSE_CODING_CRASH_PLAN``);
- :mod:`lease`    — lease files + progress heartbeats (crashed vs hung
  vs still-running, for the pipeline supervisor);
- :mod:`watchdog` — tunnel socket probe + hang classification
  (retry / degrade-to-CPU / halt).

See docs/ARCHITECTURE.md §10 for the design and the fault-site naming
scheme (§11 for the crash/lease/watchdog layer);
tests/test_resilience.py is the fault-matrix suite and
tests/test_pipeline_chaos.py the process-kill chaos matrix.
"""

from sparse_coding_tpu.resilience.breaker import CircuitBreaker
from sparse_coding_tpu.resilience.crash import (
    CRASH_SITES,
    CrashPlan,
    CrashSpec,
    crash_barrier,
    install_crash_plan,
    parse_crash_plan,
    register_crash_site,
)
from sparse_coding_tpu.resilience.errors import (
    CheckpointCorruptionError,
    ChunkCorruptionError,
    DivergenceHaltError,
    ResilienceError,
    UndersizedInputError,
    UnknownFaultSiteError,
)
from sparse_coding_tpu.resilience.faults import (
    FAULT_SITES,
    FaultPlan,
    FaultSpec,
    InjectedFault,
    fault_point,
    inject,
    install_plan,
    parse_fault_plan,
    register_fault_site,
    reload_from_env,
)
from sparse_coding_tpu.resilience.lease import (
    Lease,
    LeaseInfo,
    lease_state,
    read_lease,
)
from sparse_coding_tpu.resilience.preempt import PreemptionGuard, SweepPreempted
from sparse_coding_tpu.resilience.retry import retry_io
from sparse_coding_tpu.resilience.watchdog import (
    classify_hang,
    diagnose_hang,
    probe_tunnel,
)

__all__ = [
    "CRASH_SITES",
    "CircuitBreaker",
    "CheckpointCorruptionError",
    "ChunkCorruptionError",
    "CrashPlan",
    "CrashSpec",
    "DivergenceHaltError",
    "FAULT_SITES",
    "FaultPlan",
    "FaultSpec",
    "InjectedFault",
    "Lease",
    "LeaseInfo",
    "PreemptionGuard",
    "ResilienceError",
    "SweepPreempted",
    "UndersizedInputError",
    "UnknownFaultSiteError",
    "classify_hang",
    "crash_barrier",
    "diagnose_hang",
    "fault_point",
    "inject",
    "install_crash_plan",
    "install_plan",
    "lease_state",
    "parse_crash_plan",
    "parse_fault_plan",
    "probe_tunnel",
    "read_lease",
    "register_crash_site",
    "register_fault_site",
    "reload_from_env",
    "retry_io",
]
