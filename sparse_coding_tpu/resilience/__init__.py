"""Deterministic fault injection + the hardening it exercises.

The production north star (ROADMAP.md) is a system that runs unattended —
long multi-config sweeps surviving preemption and serving heavy traffic
through partial failure. This package is the failure-handling substrate,
built so that every handler is *driven by injected faults in CI* rather
than assumed:

- :mod:`faults`   — named fault sites + Nth-hit :class:`FaultPlan`s
  (in code via :func:`inject`, or ``SPARSE_CODING_FAULT_PLAN`` env);
- :mod:`errors`   — the typed failure taxonomy (corruption vs transient);
- :mod:`retry`    — bounded retry-with-backoff for transient I/O;
- :mod:`atomic`   — tmp+fsync+rename write discipline;
- :mod:`manifest` — content digests + checkpoint digest manifests;
- :mod:`breaker`  — the serving circuit breaker;
- :mod:`preempt`  — SIGTERM → checkpoint-and-exit for sweeps.

See docs/ARCHITECTURE.md §10 for the design and the fault-site naming
scheme; tests/test_resilience.py is the fault-matrix suite.
"""

from sparse_coding_tpu.resilience.breaker import CircuitBreaker
from sparse_coding_tpu.resilience.errors import (
    CheckpointCorruptionError,
    ChunkCorruptionError,
    ResilienceError,
)
from sparse_coding_tpu.resilience.faults import (
    FAULT_SITES,
    FaultPlan,
    FaultSpec,
    InjectedFault,
    fault_point,
    inject,
    install_plan,
    parse_fault_plan,
    register_fault_site,
    reload_from_env,
)
from sparse_coding_tpu.resilience.preempt import PreemptionGuard, SweepPreempted
from sparse_coding_tpu.resilience.retry import retry_io

__all__ = [
    "CircuitBreaker",
    "CheckpointCorruptionError",
    "ChunkCorruptionError",
    "FAULT_SITES",
    "FaultPlan",
    "FaultSpec",
    "InjectedFault",
    "PreemptionGuard",
    "ResilienceError",
    "SweepPreempted",
    "fault_point",
    "inject",
    "install_plan",
    "parse_fault_plan",
    "register_fault_site",
    "reload_from_env",
    "retry_io",
]
