"""Deterministic fault injection: named sites, Nth-hit trigger plans.

Production failures (torn writes, bit flips, flaky I/O, dispatch errors)
are rare and non-reproducible; the handlers that survive them rot unless
they are exercised on every CI run. This module gives every failure-prone
operation a **named fault site** — a single `fault_point(site, payload)`
call on its hot path — and lets a test (or an operator, via the
``SPARSE_CODING_FAULT_PLAN`` env var) install a :class:`FaultPlan` that
fires a chosen fault on exactly the Nth hit of a site. Counting is
per-plan and lock-protected, so a plan replays identically across runs
and across the threaded serving path.

Canonical sites (hosts register theirs at import; the canonical set is
pre-registered here so env plans validate before any host module loads):

====================  =====================================================
``chunk.read``        ChunkStore._finish_raw — every chunk load, both the
                      numpy and native-prefetch paths
``chunk.write``       ChunkWriter._write — every chunk flush (inside the
                      bounded-retry scope)
``ckpt.save``         save_ensemble / save_pytree / orbax save
``ckpt.restore``      restore_ensemble / restore_pytree / orbax restore
``serve.dispatch``    ServingEngine.run_padded — immediately before the
                      compiled device call
``lock.acquire``      bench.py tunnel-flock acquisition attempt
``obs.sink.write``    obs/sink.py EventSink.emit — every observability
                      event line append (drops, never raises)
``xcache.load``       xcache/store.py ExecutableStore.load — every
                      executable-cache entry read (corrupt/stale entries
                      fall back to a fresh compile)
``sweep.anomaly``     train/guardian.py — every host batch in the sweep
                      hot loop (mode=nan: non-finite-input incident;
                      mode=error + message member=<i>: per-member
                      divergence drill)
====================  =====================================================

Plan syntax (``SPARSE_CODING_FAULT_PLAN`` or :func:`parse_fault_plan`):

- compact: ``site:key=val,key=val`` entries joined by ``;`` —
  ``"chunk.read:nth=3,mode=error,error=OSError;serve.dispatch:nth=1,count=4"``
- JSON: a list of spec objects with the same keys.

Spec keys: ``nth`` (1-based hit that first fires, default 1), ``count``
(how many consecutive hits fire, default 1; 0 = every hit from nth on),
``mode`` (``error`` raises a typed injected exception; ``corrupt``
bit-flips the payload an array/bytes site passes through; ``nan`` writes
one NaN into a float-array payload — the divergence/garbage-data drill
for finite guards, a failure class a single bit flip cannot reproduce
deterministically), ``error`` (exception class name for mode=error),
``message``, ``seed`` (byte/element offset selector for
mode=corrupt/nan).

Injected exceptions subclass BOTH the requested builtin (so real handlers
— retry loops, breakers — treat them exactly like the genuine failure)
and :class:`InjectedFault` (so tests can assert the failure was ours).
"""

from __future__ import annotations

import json
import os
import threading
from dataclasses import dataclass, field
from typing import Optional, Sequence

from sparse_coding_tpu.resilience.errors import UnknownFaultSiteError

ENV_VAR = "SPARSE_CODING_FAULT_PLAN"

# site name -> one-line description; hosts add theirs via register_fault_site
FAULT_SITES: dict[str, str] = {
    "chunk.read": "chunk store read (numpy and native-prefetch paths)",
    "chunk.write": "chunk store write/flush",
    "ckpt.save": "checkpoint save (msgpack and orbax backends)",
    "ckpt.restore": "checkpoint restore (msgpack and orbax backends)",
    "serve.dispatch": "serving engine compiled-program dispatch",
    "lock.acquire": "tunnel flock acquisition attempt",
    "obs.sink.write": "observability event-sink line append (obs/sink.py)",
    "xcache.load": "executable-cache entry load (xcache/store.py)",
    # seeded here (not only registered at train/guardian.py import): a
    # child process parses SPARSE_CODING_FAULT_PLAN lazily at its FIRST
    # fault_point hit — often obs.sink.write at startup, before the sweep
    # (and therefore guardian) modules ever import
    "sweep.anomaly": "training-batch anomaly injection — every host batch "
                     "passes through this site in the sweep hot loop "
                     "(train/guardian.py); mode=nan poisons the batch "
                     "(non-finite-input incident), mode=error with "
                     "message member=<i> poisons that member's loss-scale "
                     "buffer (per-member divergence drill)",
    "obs.trace.capture": "managed profiler capture — begin and atomic "
                         "finalize (obs/trace.py)",
    "obs.ledger.append": "perf-ledger row append (obs/ledger.py)",
    # seeded here (not only registered at pipeline/fleet*.py import): a
    # fleet worker's STEP children inherit the scheduler's env plan and
    # parse it at their first fault_point — long before (and without
    # ever) importing the fleet modules
    "fleet.enqueue": "fleet queue admission — the durable run.enqueue "
                     "append (pipeline/fleet_queue.py)",
    "fleet.place": "fleet placement decision — before the durable "
                   "run.place append (pipeline/fleet.py)",
    "fleet.preempt": "fleet preemption — before the run.preempt append "
                     "+ SIGTERM (pipeline/fleet.py)",
    # seeded here (not only registered at catalog module import): the
    # catalog pipeline step child inherits the env plan and parses it at
    # its first fault_point — often obs.sink.write at startup, before
    # catalog/build.py or catalog/serve.py ever import
    "catalog.build": "catalog build I/O — the artifact-set read and "
                     "every chunk-stats accumulation step "
                     "(catalog/build.py)",
    "catalog.query": "catalog query path — before the index lookup / "
                     "gateway submit of one feature.* request "
                     "(catalog/serve.py)",
    # seeded here (not only registered at pipeline/plane.py import): the
    # arbiter shares a process with fleet workers' env plans — children
    # parse the plan at their first fault_point, before plane.py imports
    "plane.scale": "elastic plane — before applying one gateway replica "
                   "scale action (activate spare / drain) "
                   "(pipeline/plane.py)",
    "plane.rebalance": "elastic plane — before the durable "
                       "plane.rebalance record append "
                       "(pipeline/plane.py)",
    # seeded here (not only registered at groups module import): the
    # `group` pipeline step child inherits the env plan and parses it at
    # its first fault_point — often obs.sink.write at startup, before
    # groups/similarity.py or groups/assign.py ever import
    "groups.similarity": "group-SAE similarity pass — every digest-"
                         "verified sampled-chunk read feeding the "
                         "pairwise layer-similarity accumulation "
                         "(groups/similarity.py)",
    "groups.build": "group-SAE assignment build I/O — the durable "
                    "writes of similarity.npy and the per-group pooled-"
                    "store manifests, before groups.json "
                    "(groups/assign.py)",
    # seeded here (not only registered at fsck import): the supervisor's
    # resume preflight audits BEFORE any step child spawns, and a CLI
    # fsck process may parse an env plan at its very first read
    "fsck.scan": "fsck audit read — every artifact byte-read the checkers "
                 "perform (fsck/checkers.py _read_bytes); mode=error "
                 "degrades the file to an 'unreadable' finding, "
                 "mode=corrupt flips a read byte so a sound tree reports "
                 "digest mismatches (scan must still complete)",
}


def register_fault_site(name: str, description: str) -> str:
    """Idempotently register a fault site (host modules call this at
    import so the registry documents every live site)."""
    FAULT_SITES[name] = description
    return name


class InjectedFault(Exception):
    """Marker mixin: every exception raised by fault injection carries
    this base, so tests can tell injected failures from genuine ones."""


_ERROR_BASES: dict[str, type] = {
    "OSError": OSError,
    "IOError": OSError,
    "RuntimeError": RuntimeError,
    "ValueError": ValueError,
    "TimeoutError": TimeoutError,
    "ConnectionError": ConnectionError,
    "MemoryError": MemoryError,
}
_injected_types: dict[type, type] = {}


def _injected_type(base: type) -> type:
    t = _injected_types.get(base)
    if t is None:
        t = type(f"Injected{base.__name__}", (InjectedFault, base), {})
        _injected_types[base] = t
    return t


@dataclass(frozen=True)
class FaultSpec:
    """One fault: fires on hits ``nth .. nth+count-1`` of ``site``."""

    site: str
    nth: int = 1
    count: int = 1
    mode: str = "error"  # "error" | "corrupt"
    error: str = "OSError"
    message: str = "injected fault"
    seed: int = 0

    def __post_init__(self):
        if self.site not in FAULT_SITES:
            # typed + eager: a typo'd site in SPARSE_CODING_FAULT_PLAN must
            # fail the plan parse loudly, never silently disable the fault
            raise UnknownFaultSiteError(self.site, FAULT_SITES, kind="fault")
        if self.mode not in ("error", "corrupt", "nan"):
            raise ValueError(f"unknown fault mode {self.mode!r}")
        if self.mode == "error" and self.error not in _ERROR_BASES:
            raise ValueError(
                f"unknown error type {self.error!r} "
                f"(supported: {sorted(_ERROR_BASES)})")
        if self.nth < 1:
            raise ValueError("nth is 1-based and must be >= 1")
        if self.count < 0:
            raise ValueError("count must be >= 0 (0 = every hit from nth)")

    def fires_on(self, hit: int) -> bool:
        if hit < self.nth:
            return False
        return self.count == 0 or hit < self.nth + self.count

    def build_error(self) -> BaseException:
        return _injected_type(_ERROR_BASES[self.error])(
            f"{self.message} [site={self.site}]")


@dataclass
class FaultPlan:
    """An installed set of :class:`FaultSpec`s with per-site hit counters.

    Deterministic: hit k of a site fires iff some spec covers k,
    independent of wall clock, interleaving, or prior runs. ``fired``
    records every (site, hit_index) that triggered, for assertions."""

    specs: list[FaultSpec] = field(default_factory=list)
    hits: dict[str, int] = field(default_factory=dict)
    fired: list[tuple[str, int]] = field(default_factory=list)
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)

    def hit(self, site: str) -> Optional[FaultSpec]:
        with self._lock:
            n = self.hits.get(site, 0) + 1
            self.hits[site] = n
            for spec in self.specs:
                if spec.site == site and spec.fires_on(n):
                    self.fired.append((site, n))
                    return spec
        return None

    def fired_count(self, site: str) -> int:
        with self._lock:
            return sum(1 for s, _ in self.fired if s == site)


_active: Optional[FaultPlan] = None
_env_checked = False
_install_lock = threading.Lock()


def active_plan() -> Optional[FaultPlan]:
    """The installed plan; lazily loads ``SPARSE_CODING_FAULT_PLAN`` from
    the environment exactly once if nothing was installed in code."""
    global _active, _env_checked
    if _active is None and not _env_checked:
        with _install_lock:
            if _active is None and not _env_checked:
                text = os.environ.get(ENV_VAR, "").strip()
                if text:
                    _active = parse_fault_plan(text)
                _env_checked = True
    return _active


def install_plan(plan: Optional[FaultPlan]) -> Optional[FaultPlan]:
    """Install (or with None, clear) the active plan; returns the previous
    one. Also re-arms the env lookup so clearing in tests is hermetic."""
    global _active, _env_checked
    with _install_lock:
        prev, _active = _active, plan
        _env_checked = True  # explicit install wins over the env var
    return prev


def reload_from_env() -> Optional[FaultPlan]:
    """Force a re-parse of ``SPARSE_CODING_FAULT_PLAN`` and return the
    newly-installed plan (tests; operators changing the plan between runs
    never need this — a fresh process parses lazily)."""
    text = os.environ.get(ENV_VAR, "").strip()
    plan = parse_fault_plan(text) if text else None
    install_plan(plan)
    return plan


class inject:
    """Context manager: install a plan for the block, restore the previous
    plan after. ``inject(FaultSpec(...), ...)`` or keyword shorthand
    ``inject(site="chunk.read", nth=2)`` for a single spec. The plan
    object is available as the ``as`` target for fired-count asserts."""

    def __init__(self, *specs: FaultSpec, **one_spec):
        if one_spec:
            specs = specs + (FaultSpec(**one_spec),)
        self.plan = FaultPlan(specs=list(specs))
        self._prev: Optional[FaultPlan] = None

    def __enter__(self) -> FaultPlan:
        self._prev = install_plan(self.plan)
        return self.plan

    def __exit__(self, *exc) -> None:
        install_plan(self._prev)


def _corrupt_payload(payload, spec: FaultSpec):
    """Deterministically flip one bit of an array/bytes payload (the
    ``seed`` selects the byte). Sites that pass no payload cannot host a
    corrupt-mode fault — that is a plan bug, so fail loudly."""
    import numpy as np

    if payload is None:
        raise ValueError(
            f"fault site {spec.site!r} carries no payload; mode=corrupt "
            "is only valid at data-bearing sites (use mode=error)")
    if isinstance(payload, (bytes, bytearray)):
        buf = bytearray(payload)
        buf[spec.seed % len(buf)] ^= 0x01
        return bytes(buf)
    arr = np.array(payload, copy=True)
    flat = arr.view(np.uint8).reshape(-1)
    flat[spec.seed % flat.size] ^= 0x01
    return arr


def _nan_payload(payload, spec: FaultSpec):
    """Deterministically overwrite one float element with NaN (the
    ``seed`` selects the element). The divergence-drill twin of
    ``_corrupt_payload``: a bit flip produces a wrong-but-usually-finite
    value, while finite guards need a guaranteed non-finite input."""
    import numpy as np

    if payload is None:
        raise ValueError(
            f"fault site {spec.site!r} carries no payload; mode=nan is "
            "only valid at float-array sites (use mode=error)")
    arr = np.array(payload, copy=True)
    # floatness by capability, not np.floating lineage: ml_dtypes types
    # (bfloat16 — the train_dtype='bfloat16' ingest payload) hold NaN but
    # are not np.floating subdtypes; int dtypes raise on the cast
    try:
        holds_nan = bool(np.isnan(np.asarray(np.nan).astype(arr.dtype)))
    except (TypeError, ValueError):
        holds_nan = False
    if not holds_nan:
        raise ValueError(
            f"fault site {spec.site!r} payload dtype {arr.dtype} cannot "
            "hold NaN; mode=nan needs a float-array payload")
    arr.reshape(-1)[spec.seed % arr.size] = arr.dtype.type(np.nan)
    return arr


def fault_point(site: str, payload=None):
    """The single injection hook every hardened path calls. Returns the
    payload (possibly mutated by an active corrupt-/nan-mode fault);
    raises the injected exception for error-mode faults. Near-zero cost
    when no plan is active — and a fired mutation always returns a COPY,
    so callers can tell an injected payload from the original by
    identity."""
    plan = active_plan()
    if plan is None:
        return payload
    spec = plan.hit(site)
    if spec is None:
        return payload
    if spec.mode == "error":
        raise spec.build_error()
    if spec.mode == "nan":
        return _nan_payload(payload, spec)
    return _corrupt_payload(payload, spec)


def parse_plan_entries(text: str, keys: Sequence[str],
                       int_keys: Sequence[str],
                       label: str = "fault-plan") -> list[dict]:
    """Shared plan grammar (JSON list or compact ``site:key=val,...;...``)
    -> a list of spec-kwargs dicts. `SPARSE_CODING_FAULT_PLAN` and
    `SPARSE_CODING_CRASH_PLAN` use the same Nth-hit grammar; `keys` names
    the spec fields each accepts."""
    text = text.strip()
    if text.startswith("[") or text.startswith("{"):
        raw = json.loads(text)
        if isinstance(raw, dict):
            raw = [raw]
        return [dict(entry) for entry in raw]
    entries: list[dict] = []
    for entry in text.split(";"):
        entry = entry.strip()
        if not entry:
            continue
        site, _, rest = entry.partition(":")
        kwargs: dict = {"site": site.strip()}
        for pair in filter(None, (p.strip() for p in rest.split(","))):
            key, sep, val = pair.partition("=")
            if not sep or key not in keys:
                raise ValueError(
                    f"bad {label} pair {pair!r} in entry {entry!r} "
                    f"(expected key=value with key in {'/'.join(keys)})")
            kwargs[key] = int(val) if key in int_keys else val
        entries.append(kwargs)
    return entries


def parse_fault_plan(text: str) -> FaultPlan:
    """Parse the env-var / CLI plan syntax (JSON list or compact
    ``site:key=val,...;site2:...`` string) into a validated plan. Unknown
    site names raise a typed :class:`UnknownFaultSiteError` eagerly."""
    entries = parse_plan_entries(
        text, keys=("nth", "count", "mode", "error", "message", "seed"),
        int_keys=("nth", "count", "seed"), label="fault-plan")
    return FaultPlan(specs=[FaultSpec(**e) for e in entries])
