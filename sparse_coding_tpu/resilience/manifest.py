"""Content digests and checkpoint digest manifests.

- :func:`array_sha256` / :func:`bytes_sha256`: the digest primitive the
  chunk store and msgpack checkpoint backend record at write time and
  verify at read time.
- :func:`write_dir_manifest` / :func:`verify_dir_manifest`: a sidecar
  JSON mapping every file under a directory tree (the orbax checkpoint
  dir) to its sha256 + size, written AFTER the backend's own commit is
  durable. Restore verifies the manifest before handing the directory to
  orbax, turning silent shard corruption into a typed
  :class:`~sparse_coding_tpu.resilience.errors.CheckpointCorruptionError`
  that the sweep's resume path can fall back from.

The manifest lives NEXT TO the checkpoint directory (``<dir>.manifest
.json``), never inside it — orbax owns its directory contents.
"""

from __future__ import annotations

import hashlib
import json
from pathlib import Path

import numpy as np

from sparse_coding_tpu.resilience.atomic import atomic_write_text
from sparse_coding_tpu.resilience.errors import CheckpointCorruptionError

MANIFEST_SUFFIX = ".manifest.json"

# Key under which small JSON ledgers (guardian.json, quarantine.json)
# embed a digest of their own payload. The digest covers the canonical
# ``json.dumps(body, sort_keys=True)`` bytes of every OTHER key, so any
# writer that dumps with sorted keys produces a verifiable file and a
# digest-less legacy file stays loadable (readers treat absence as
# "unverified", fsck flags it STALE).
PAYLOAD_DIGEST_KEY = "payload_sha256"


def bytes_sha256(data: bytes) -> str:
    return hashlib.sha256(data).hexdigest()


def array_sha256(arr) -> str:
    """Digest of an array's raw C-order bytes — identical whether the
    array came from np.load, the native pread path, or the writer's
    pre-save buffer, so one recorded digest covers every read path."""
    return hashlib.sha256(np.ascontiguousarray(arr).tobytes()).hexdigest()


def file_sha256(path: str | Path, block: int = 1 << 20) -> str:
    h = hashlib.sha256()
    with open(path, "rb") as f:
        while True:
            chunk = f.read(block)
            if not chunk:
                return h.hexdigest()
            h.update(chunk)


def _payload_body_digest(payload: dict) -> str:
    body = {k: payload[k] for k in payload if k != PAYLOAD_DIGEST_KEY}
    return bytes_sha256(json.dumps(body, sort_keys=True).encode())


def embed_payload_digest(payload: dict) -> dict:
    """Return ``payload`` with :data:`PAYLOAD_DIGEST_KEY` set to the
    sha256 of its canonical dump. Pure — the input dict is not mutated,
    and re-embedding an already-digested payload is idempotent."""
    out = {k: payload[k] for k in payload if k != PAYLOAD_DIGEST_KEY}
    out[PAYLOAD_DIGEST_KEY] = _payload_body_digest(out)
    return out


def check_payload_digest(payload) -> str:
    """``"ok"`` (digest present and matches), ``"absent"`` (legacy
    digest-less payload — loadable, unverified), or ``"mismatch"``.
    Non-dict payloads are ``"mismatch"`` — they cannot carry a digest."""
    if not isinstance(payload, dict):
        return "mismatch"
    want = payload.get(PAYLOAD_DIGEST_KEY)
    if want is None:
        return "absent"
    return "ok" if _payload_body_digest(payload) == want else "mismatch"


def manifest_path(target: str | Path) -> Path:
    target = Path(target)
    return target.parent / (target.name + MANIFEST_SUFFIX)


def write_dir_manifest(target: str | Path) -> Path:
    """Record sha256+size of every file under ``target`` (recursive) into
    the ``<target>.manifest.json`` sidecar. Call only once the backend's
    own write is durable (e.g. after orbax wait_until_finished)."""
    target = Path(target)
    files = sorted(p for p in target.rglob("*") if p.is_file())
    entries = {
        str(p.relative_to(target)): {"sha256": file_sha256(p),
                                     "size": p.stat().st_size}
        for p in files}
    out = manifest_path(target)
    atomic_write_text(out, json.dumps({"files": entries}, indent=2))
    return out


def verify_dir_manifest(target: str | Path) -> bool:
    """Verify ``target`` against its sidecar manifest. Returns False when
    no manifest exists (pre-manifest checkpoint — nothing to verify);
    raises :class:`CheckpointCorruptionError` naming the first damaged or
    missing file otherwise."""
    target = Path(target)
    side = manifest_path(target)
    if not side.exists():
        return False
    try:
        entries = json.loads(side.read_text())["files"]
    except (ValueError, KeyError) as e:
        raise CheckpointCorruptionError(target,
                                        f"unreadable manifest: {e}") from e
    for rel, want in entries.items():
        p = target / rel
        if not p.exists():
            raise CheckpointCorruptionError(target,
                                            f"manifest file missing: {rel}")
        if p.stat().st_size != want["size"]:
            raise CheckpointCorruptionError(
                target, f"size mismatch for {rel}: "
                f"{p.stat().st_size} != {want['size']}")
        if file_sha256(p) != want["sha256"]:
            raise CheckpointCorruptionError(target,
                                            f"digest mismatch for {rel}")
    return True
