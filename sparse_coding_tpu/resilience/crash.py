"""Named crash barriers: deterministic whole-process SIGKILL injection.

PR 2's fault sites exercise *in-process* failure handlers; this module
exercises the crash-only contract itself — "any process may die at any
instruction". A **crash barrier** is a single ``crash_barrier(site)`` call
placed immediately after (or between) the durable effects whose ordering
the recovery story depends on. A :class:`CrashPlan` — installed in code or
via ``SPARSE_CODING_CRASH_PLAN`` (same Nth-hit grammar as
``SPARSE_CODING_FAULT_PLAN``, keys ``nth``/``count`` only) — SIGKILLs the
process at exactly the Nth hit of a site. SIGKILL is uncatchable: no
``atexit``, no buffers flushed, no finally blocks — the honest model of a
kill -9, an OOM kill, or a power cut.

Canonical sites (hosts register theirs at import, like fault sites):

====================  =====================================================
``chunk.flushed``     ChunkWriter._write — a chunk file + digest just
                      became durable; the next instruction never runs
``store.finalize``    ChunkWriter.finalize — all chunks durable, meta.json
                      (the completeness marker) NOT yet written
``sweep.chunk``       train/sweep.py — end of one chunk's training +
                      checkpoint + artifact block
``ckpt.swap``         _swap_in_checkpoint_set — after ckpt/ was renamed to
                      ckpt_prev/, before staging/ was renamed to ckpt/
                      (the worst instant of the checkpoint-set swap)
``eval.write``        pipeline eval step — results computed, output file
                      NOT yet written
``obs.sink.write``    obs/sink.py — event payload appended, commit newline
                      not yet written (the torn-tail instant)
``xcache.store``      xcache/store.py — executable-cache entry durable,
                      LRU manifest not yet updated
``shard.finalize``    data/shard_store.py — a shard's meta.json durable,
                      its shard.digest seal NOT yet written
``scrub.repair``      data/scrub.py — quarantine ledger entry durable, the
                      corrupt chunk file not yet moved aside
====================  =====================================================

The chaos matrix (tests/test_pipeline_chaos.py, marker ``chaos``) kills a
real subprocess at every barrier, restarts the supervisor, and asserts the
completed run's artifacts are bitwise-identical to an uninterrupted run.

Hit counting is per-process: a resumed child starts fresh counters, so a
plan that kills at ``nth=2`` kills every attempt at its own 2nd hit —
useful for proving forward progress under repeated kills.
"""

from __future__ import annotations

import os
import signal
import sys
import threading
from dataclasses import dataclass, field
from typing import Optional

from sparse_coding_tpu.resilience.errors import UnknownFaultSiteError
from sparse_coding_tpu.resilience.faults import parse_plan_entries

ENV_VAR = "SPARSE_CODING_CRASH_PLAN"

# site name -> one-line description; hosts add theirs via register_crash_site
CRASH_SITES: dict[str, str] = {
    "chunk.flushed": "a chunk file + digest just became durable "
                     "(ChunkWriter._write)",
    "store.finalize": "all chunks durable, meta.json not yet written "
                      "(ChunkWriter.finalize)",
    "sweep.chunk": "end of one sweep chunk's train+checkpoint+artifact block",
    "ckpt.swap": "mid checkpoint-set swap: old set renamed to ckpt_prev/, "
                 "new set not yet renamed in",
    "eval.write": "eval results computed, output not yet written",
    "obs.sink.write": "event payload appended, commit newline not yet "
                      "written (obs/sink.py — the torn-tail instant)",
    "xcache.store": "executable-cache entry durable, LRU manifest not yet "
                    "updated (xcache/store.py)",
    # seeded here (not only registered at host import) because a plan can
    # be parsed at a child's FIRST barrier hit — often obs.sink.write at
    # startup, before data/shard_store.py or data/scrub.py ever import
    "shard.finalize": "a shard's meta.json is durable, its shard.digest "
                      "seal not yet written (data/shard_store.py)",
    "scrub.repair": "scrub: quarantine ledger entry durable, the corrupt "
                    "chunk file not yet moved aside (data/scrub.py)",
    "guardian.rollback": "guardian incident ledger + chunk quarantine "
                         "durable, the last-good checkpoint restore not "
                         "yet performed (train/guardian.py)",
    "obs.trace.capture": "profiler stopped, trace tmp dir durable, final "
                         "rename not yet performed (obs/trace.py)",
    # seeded like shard.finalize/scrub.repair: a fleet worker's step
    # children inherit the scheduler's SPARSE_CODING_CRASH_PLAN and parse
    # it at their first barrier, without ever importing pipeline/fleet.py
    "fleet.place": "run.place queue record durable, the worker not yet "
                   "spawned (pipeline/fleet.py) — the no-run-lost/"
                   "none-double-placed instant",
    # seeded like the fleet sites: the catalog step child parses the env
    # plan at its first barrier hit, before catalog/build.py imports
    "catalog.finalize": "catalog build — every .npy array durable, "
                        "index.json (the completion marker) not yet "
                        "written (catalog/build.py)",
    # seeded like the fleet sites: worker children inherit the arbiter's
    # env plan and parse it at their first barrier, before
    # pipeline/plane.py ever imports
    "plane.rebalance": "elastic plane — rebalance record durable in the "
                       "fleet queue journal, NEITHER consumer resized "
                       "yet (pipeline/plane.py) — the no-double-booking "
                       "reconcile instant",
    # seeded like the fleet sites: the `group` step child parses the env
    # plan at its first barrier hit, before groups/assign.py imports
    "groups.finalize": "group assignment build — similarity.npy and "
                       "every per-group pooled-store manifest durable, "
                       "groups.json (the completion marker) not yet "
                       "written (groups/assign.py)",
    # seeded like the fleet sites: `python -m sparse_coding_tpu.fsck
    # --repair` children parse the env plan at their first barrier
    "fsck.repair": "fsck repair engine — immediately before applying one "
                   "repair action's durable mutation (fsck/repair.py); "
                   "SIGKILL here, restart, and the re-run repairs the "
                   "remainder to a bitwise-identical tree",
}


def register_crash_site(name: str, description: str) -> str:
    """Idempotently register a crash site (host modules call this at
    import, mirroring ``register_fault_site``)."""
    CRASH_SITES[name] = description
    return name


@dataclass(frozen=True)
class CrashSpec:
    """SIGKILL the process on hits ``nth .. nth+count-1`` of ``site``."""

    site: str
    nth: int = 1
    count: int = 1

    def __post_init__(self):
        if self.site not in CRASH_SITES:
            raise UnknownFaultSiteError(self.site, CRASH_SITES, kind="crash")
        if self.nth < 1:
            raise ValueError("nth is 1-based and must be >= 1")
        if self.count < 0:
            raise ValueError("count must be >= 0 (0 = every hit from nth)")

    def fires_on(self, hit: int) -> bool:
        if hit < self.nth:
            return False
        return self.count == 0 or hit < self.nth + self.count


@dataclass
class CrashPlan:
    """Installed set of :class:`CrashSpec`s with per-site hit counters
    (lock-protected, so counting is deterministic across threads)."""

    specs: list[CrashSpec] = field(default_factory=list)
    hits: dict[str, int] = field(default_factory=dict)
    _lock: threading.Lock = field(default_factory=threading.Lock, repr=False)

    def hit(self, site: str) -> Optional[CrashSpec]:
        with self._lock:
            n = self.hits.get(site, 0) + 1
            self.hits[site] = n
            for spec in self.specs:
                if spec.site == site and spec.fires_on(n):
                    return spec
        return None


def parse_crash_plan(text: str) -> CrashPlan:
    """Same grammar as ``SPARSE_CODING_FAULT_PLAN`` (compact or JSON), keys
    ``nth``/``count`` only. Unknown sites raise the typed
    :class:`UnknownFaultSiteError` eagerly."""
    entries = parse_plan_entries(text, keys=("nth", "count"),
                                 int_keys=("nth", "count"),
                                 label="crash-plan")
    return CrashPlan(specs=[CrashSpec(**e) for e in entries])


_active: Optional[CrashPlan] = None
_env_checked = False
_install_lock = threading.Lock()


def active_crash_plan() -> Optional[CrashPlan]:
    """The installed plan; lazily loads ``SPARSE_CODING_CRASH_PLAN`` once
    if nothing was installed in code (same lifecycle as fault plans)."""
    global _active, _env_checked
    if _active is None and not _env_checked:
        with _install_lock:
            if _active is None and not _env_checked:
                text = os.environ.get(ENV_VAR, "").strip()
                if text:
                    _active = parse_crash_plan(text)
                _env_checked = True
    return _active


def install_crash_plan(plan: Optional[CrashPlan]) -> Optional[CrashPlan]:
    """Install (or with None, clear) the active plan; returns the previous
    one. Re-arms the env lookup so clearing in tests is hermetic."""
    global _active, _env_checked
    with _install_lock:
        prev, _active = _active, plan
        _env_checked = True
    return prev


def _kill_self(site: str) -> None:  # monkeypatchable in unit tests
    # stderr is unbuffered-ish and the write is best-effort: SIGKILL gives
    # no other chance to leave a breadcrumb for the supervisor's step log
    try:
        sys.stderr.write(f"crash_barrier: SIGKILL at site {site!r}\n")
        sys.stderr.flush()
    except Exception:
        pass
    os.kill(os.getpid(), signal.SIGKILL)


def crash_barrier(site: str) -> None:
    """The single hook every crash-tested path calls. No-op without an
    active plan; SIGKILLs the process (uncatchable, nothing flushed) when
    the plan covers this hit."""
    plan = active_crash_plan()
    if plan is None:
        return
    if plan.hit(site) is not None:
        _kill_self(site)
