"""Lease files with progress heartbeats: crashed vs hung vs still-running.

A supervisor that restarts after its own death (or watches a live child)
needs to answer one question about a step it did not just spawn: is the
process that owns this step **still making progress**? The lease file is
that answer on disk:

- the step's process atomically rewrites ``<lease>.json`` (tmp+fsync+
  rename, :mod:`resilience.atomic`) at every **real progress point** —
  a chunk flushed, a training chunk finished, a bench window timed;
- a reader classifies the lease: ``missing`` (no claim), ``dead`` (owner
  pid gone — it crashed; take over), ``stale`` (owner alive but the
  heartbeat is old — it is hung; kill + diagnose), ``live`` (leave it
  alone).

Heartbeats are deliberately emitted from the WORK LOOP on the main thread,
never from a side thread: the canonical hang here is the axon TPU tunnel
wedging a process inside ``make_c_api_client`` (CLAUDE.md) — a side-thread
heartbeat would keep beating through exactly the hang the watchdog exists
to catch. Hosts call the module-level :func:`beat` (a no-op unless
``SPARSE_CODING_LEASE_PATH`` is set, so library code stays supervisor-
agnostic); rewrites are throttled to one per ``interval_s``.

pid liveness is same-host only (``os.kill(pid, 0)``); the supervisor and
its steps share a machine by construction (one TPU tunnel per host).
"""

from __future__ import annotations

import json
import os
import socket
import time
from dataclasses import dataclass
from pathlib import Path
from typing import Optional

from sparse_coding_tpu.resilience.atomic import atomic_write_text

ENV_PATH = "SPARSE_CODING_LEASE_PATH"
ENV_INTERVAL = "SPARSE_CODING_LEASE_INTERVAL_S"
# the supervisor's run correlation ID (obs/spans.py contract, docs/
# ARCHITECTURE.md §12): stamped into every lease write so beats join the
# run's journal records and events. Read directly (not via obs) to keep
# this module dependency-free.
ENV_RUN_ID = "SPARSE_CODING_RUN_ID"


@dataclass
class LeaseInfo:
    """One parsed lease file."""

    pid: int
    host: str
    step: str
    started_at: float
    beat_at: float
    seq: int


class Lease:
    """Writer side: the step process's claim on its unit of work."""

    def __init__(self, path: str | Path, step: str = "",
                 interval_s: float = 1.0, clock=time.time):
        self.path = Path(path)
        self.step = step
        self.interval_s = float(interval_s)
        self._clock = clock
        self._started = clock()
        self._last_write = 0.0
        self._seq = 0
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self.beat(force=True)  # claim immediately: a spawned-but-not-yet-
        # progressing step must look "live", not "missing"

    def beat(self, force: bool = False) -> None:
        """Record progress. Throttled to one atomic rewrite per
        ``interval_s`` so per-batch call sites stay cheap."""
        now = self._clock()
        if not force and now - self._last_write < self.interval_s:
            return
        self._seq += 1
        atomic_write_text(self.path, json.dumps({
            "pid": os.getpid(), "host": socket.gethostname(),
            "step": self.step, "started_at": self._started,
            "beat_at": now, "seq": self._seq,
            "run": os.environ.get(ENV_RUN_ID, "")}))
        self._last_write = now

    def release(self) -> None:
        self.path.unlink(missing_ok=True)


def read_lease(path: str | Path) -> Optional[LeaseInfo]:
    """Parse a lease file; None when missing or unreadable (an unreadable
    lease means no valid claim — atomic writes make torn files impossible,
    so garbage is pre-takeover debris)."""
    try:
        raw = json.loads(Path(path).read_text())
        return LeaseInfo(pid=int(raw["pid"]), host=str(raw.get("host", "")),
                         step=str(raw.get("step", "")),
                         started_at=float(raw.get("started_at", 0.0)),
                         beat_at=float(raw["beat_at"]),
                         seq=int(raw.get("seq", 0)))
    except (OSError, ValueError, KeyError, TypeError):
        return None


def pid_alive(pid: int) -> bool:
    if pid <= 0:
        return False
    try:
        os.kill(pid, 0)
    except ProcessLookupError:
        return False
    except PermissionError:
        return True  # exists, other uid
    return True


def lease_state(path: str | Path, stale_after_s: float,
                clock=time.time) -> str:
    """Classify a lease: ``missing`` | ``dead`` | ``stale`` | ``live``.

    ``dead`` = owner pid gone (crashed — safe takeover). ``stale`` = owner
    alive but no heartbeat for ``stale_after_s`` (hung — kill before
    takeover). Wall-clock staleness is same-host comparable; a beat_at in
    the future (clock step) counts as fresh rather than poisoning the
    window."""
    info = read_lease(path)
    if info is None:
        return "missing"
    if not pid_alive(info.pid):
        return "dead"
    if clock() - info.beat_at > stale_after_s:
        return "stale"
    return "live"


def seed_lease(path: str | Path, pid: int, step: str = "",
               clock=time.time, run: str = "") -> None:
    """Supervisor-side: stamp a just-spawned child's claim so the hang
    window opens at spawn time — the child overwrites with its own beats
    once its interpreter is up (jax import time counts against the stale
    budget by design: a child wedged in backend init never beats)."""
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    now = clock()
    atomic_write_text(path, json.dumps({
        "pid": int(pid), "host": socket.gethostname(), "step": step,
        "started_at": now, "beat_at": now, "seq": 0,
        "run": run or os.environ.get(ENV_RUN_ID, "")}))


# -- module-global heartbeat hook (host work loops call beat()) --------------

_active: Optional[Lease] = None
_env_checked = False


def configure(lease: Optional[Lease]) -> Optional[Lease]:
    """Install (or clear) the process's active lease; returns the previous
    one. Explicit configuration wins over the env lookup."""
    global _active, _env_checked
    prev, _active = _active, lease
    _env_checked = True
    return prev


def configure_from_env(step: str = "") -> Optional[Lease]:
    """Create the process lease from ``SPARSE_CODING_LEASE_PATH`` (no-op
    returning None when unset)."""
    path = os.environ.get(ENV_PATH, "").strip()
    if not path:
        configure(None)
        return None
    interval = float(os.environ.get(ENV_INTERVAL, "1.0"))
    lease = Lease(path, step=step, interval_s=interval)
    configure(lease)
    return lease


def beat() -> None:
    """Progress heartbeat for hosted work loops (harvest drain, sweep chunk
    loop, bench timing windows). Lazily self-configures from the env on
    first call so hosts need no supervisor plumbing; near-zero cost when no
    lease is configured."""
    global _env_checked
    if _active is None:
        if _env_checked:
            return
        _env_checked = True
        if configure_from_env() is None:
            return
    _active.beat()
