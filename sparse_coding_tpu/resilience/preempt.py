"""Cooperative SIGTERM preemption for long unattended sweeps.

Preemptible capacity (and the unattended tunnel-recovery loop) delivers
SIGTERM, not SIGKILL — a window to save and exit. The guard converts the
signal into a flag the sweep polls at chunk boundaries: the chunk is the
unit of resumable work (the data-order RNG is checkpointed per chunk), so
finishing the in-flight chunk, checkpointing, and raising
:class:`SweepPreempted` continues BITWISE-identically on resume — the
same guarantee as the crash-resume path (docs/ARCHITECTURE.md §4), now
exercised on the graceful-shutdown path too.

Signal handlers are process-global and main-thread-only; the guard
restores the previous handler on exit and degrades to a purely
cooperative flag (``request()``) off the main thread.
"""

from __future__ import annotations

import signal
import threading
from typing import Optional


class SweepPreempted(RuntimeError):
    """Raised by ``train/sweep.py`` after a preemption-triggered
    checkpoint completed: state through ``chunks_done`` chunks is durable
    and ``sweep(..., resume=True)`` continues exactly. The CLI treats
    this as a clean (exit-0) shutdown."""

    def __init__(self, chunks_done: int):
        super().__init__(
            f"sweep preempted: checkpointed after chunk {chunks_done}; "
            f"resume with resume=True")
        self.chunks_done = int(chunks_done)


class PreemptionGuard:
    """Context manager installing a SIGTERM (by default) flag handler."""

    def __init__(self, signals: tuple = (signal.SIGTERM,)):
        self._signals = signals
        self._event = threading.Event()
        self._previous: dict[int, object] = {}
        self._installed = False

    def __enter__(self) -> "PreemptionGuard":
        if threading.current_thread() is threading.main_thread():
            for sig in self._signals:
                self._previous[sig] = signal.signal(sig, self._handle)
            self._installed = True
        return self

    def __exit__(self, *exc) -> None:
        if self._installed:
            for sig, prev in self._previous.items():
                signal.signal(sig, prev)
            self._previous.clear()
            self._installed = False

    def _handle(self, signum, frame) -> None:
        self._event.set()

    def request(self) -> None:
        """Cooperative trigger (tests, embedding frameworks with their own
        signal plumbing)."""
        self._event.set()

    @property
    def requested(self) -> bool:
        return self._event.is_set()

    def signal_received(self) -> Optional[bool]:
        return self._event.is_set()
