"""Bounded retry-with-backoff for transient I/O.

One policy, used by every hardened host path (chunk reads/writes, the
serving dispatch retry loop supplies its own budget on top). Deliberately
tiny: retries are for *transient* failures only — corruption
(:class:`~sparse_coding_tpu.resilience.errors.ChunkCorruptionError`,
``CheckpointCorruptionError``) must never be retried, so those types are
excluded by construction via ``retry_on``.
"""

from __future__ import annotations

import time
from typing import Callable, Optional, Sequence

TRANSIENT_IO_ERRORS: tuple[type, ...] = (OSError,)  # incl. Timeout/Connection


def retry_io(fn: Callable, *, attempts: int = 3, base_delay_s: float = 0.01,
             retry_on: Sequence[type] = TRANSIENT_IO_ERRORS,
             sleep: Callable[[float], None] = time.sleep,
             on_retry: Optional[Callable[[int, BaseException], None]] = None,
             jitter: float = 0.0, rng=None):
    """Call ``fn()`` with up to ``attempts`` tries and exponential backoff
    (``base_delay_s * 2**i`` between tries). The last failure propagates
    unchanged — bounded means bounded, no infinite-retry hangs.

    ``jitter`` > 0 scales each delay by ``1 + U[0, jitter)`` drawn from
    ``rng`` (a ``numpy.random.Generator``; required when jitter is set) —
    decorrelates a herd of clients retrying the same shared resource. The
    backoff stays DETERMINISTIC under a seeded rng: same seed, same delay
    sequence (tests/test_resilience.py pins this)."""
    if attempts < 1:
        raise ValueError("attempts must be >= 1")
    if jitter < 0:
        raise ValueError("jitter must be >= 0")
    if jitter > 0 and rng is None:
        raise ValueError("jitter needs an explicit seeded rng — an implicit "
                         "global RNG would make retry timing irreproducible")
    retry_on = tuple(retry_on)
    for i in range(attempts):
        try:
            return fn()
        except retry_on as e:  # noqa: PERF203 — the loop IS the policy
            if i == attempts - 1:
                raise
            if on_retry is not None:
                on_retry(i, e)
            delay = base_delay_s * (2 ** i)
            if jitter > 0:
                delay *= 1.0 + jitter * float(rng.random())
            sleep(delay)
