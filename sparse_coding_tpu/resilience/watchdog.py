"""Hang diagnosis: socket-probe the TPU tunnel, then decide what a hung
step means.

The canonical hang on this container is the axon TPU tunnel: its plugin
initializes inside every jax process and a wedged tunnel blocks the
process forever in ``make_c_api_client`` at ~0% CPU (CLAUDE.md). The
repo's diagnosis recipe — probe ports 2024/8082/8083 with a socket
connect *before theorizing* — is implemented here as data, so the
pipeline supervisor (and ``__graft_entry__``'s dryrun watchdog) can act
on it mechanically:

- tunnel **not configured** (no ``PALLAS_AXON_POOL_IPS``): the hang is
  not tunnel-related → **retry** the step;
- tunnel configured but **unreachable**: the endpoint is down → a retry
  would wedge again; **degrade to CPU** (respawn with the plugin
  stripped) so the run completes with a labeled CPU artifact;
- tunnel configured and **reachable**: the endpoint answers but our
  client is stuck — the known server-side session-lease wedge, which
  nothing local clears → **halt** and point the operator at the runbook
  (a retrying client would become the second tunnel process that wedges
  it harder).

This module must stay import-light (no jax): it runs inside watchdog
threads while the main thread may be stuck in native code.
"""

from __future__ import annotations

import os
import socket
from typing import Optional, Sequence

TUNNEL_ENV = "PALLAS_AXON_POOL_IPS"
TUNNEL_PORTS = (2024, 8082, 8083)
# repo-wide tunnel mutual exclusion (CLAUDE.md): every tunnel-touching
# process serializes on this flock
TUNNEL_LOCK = "/tmp/axon_tunnel.lock"
RUNBOOK = "docs/RUNBOOK_TUNNEL.md"

# classify_hang verdicts
RETRY = "retry"
DEGRADE_CPU = "degrade-cpu"
HALT = "halt"


def tunnel_hosts(env: Optional[dict] = None) -> list[str]:
    """Hosts from ``PALLAS_AXON_POOL_IPS`` (comma/space separated)."""
    raw = (env if env is not None else os.environ).get(TUNNEL_ENV, "")
    return [h for h in raw.replace(",", " ").split() if h]


def probe_tunnel(hosts: Optional[Sequence[str]] = None,
                 ports: Sequence[int] = TUNNEL_PORTS,
                 timeout_s: float = 2.0, connect=None) -> dict:
    """Socket-connect every host:port; returns a JSON-able report:
    ``{"configured", "endpoints": {"h:p": bool}, "reachable"}``.
    ``connect`` is injectable for deterministic tests."""
    if hosts is None:
        hosts = tunnel_hosts()
    if connect is None:
        connect = socket.create_connection
    endpoints: dict[str, bool] = {}
    for host in hosts:
        for port in ports:
            key = f"{host}:{port}"
            try:
                conn = connect((host, int(port)), timeout_s)
                try:
                    conn.close()
                except Exception:
                    pass
                endpoints[key] = True
            except OSError:
                endpoints[key] = False
    return {"configured": bool(hosts), "endpoints": endpoints,
            "reachable": any(endpoints.values())}


def classify_hang(probe: dict) -> str:
    """Map a :func:`probe_tunnel` report to a supervisor action (see the
    module docstring for the reasoning): RETRY | DEGRADE_CPU | HALT."""
    if not probe.get("configured"):
        return RETRY
    if not probe.get("reachable"):
        return DEGRADE_CPU
    return HALT


def diagnose_hang(prober=probe_tunnel) -> dict:
    """One-call hang diagnosis: probe + verdict + the operator pointer,
    shaped for journaling."""
    probe = prober()
    action = classify_hang(probe)
    return {"probe": probe, "action": action, "runbook": RUNBOOK}


def format_diagnosis(diag: dict) -> str:
    probe = diag.get("probe", {})
    up = [k for k, v in probe.get("endpoints", {}).items() if v]
    down = [k for k, v in probe.get("endpoints", {}).items() if not v]
    if not probe.get("configured"):
        detail = "tunnel not configured (no PALLAS_AXON_POOL_IPS)"
    else:
        detail = (f"tunnel endpoints up={up or 'none'} down={down or 'none'}")
    return (f"hang diagnosis: {detail}; action={diag.get('action')}; "
            f"see {diag.get('runbook', RUNBOOK)}")
