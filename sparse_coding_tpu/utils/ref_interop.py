"""Reference-artifact interop: ingest HoagyC/sparse_coding outputs.

The reference persists two artifact families this framework must be able to
read so reference-trained results can be evaluated/compared in place:

- ``learned_dicts.pt``: a torch pickle of ``[(LearnedDict, hyperparams), …]``
  tuples (reference: big_sweep.py:378-384, basic_l1_sweep.py:108-115). The
  pickle references live classes from the reference's ``autoencoders.*``
  modules, which are not installed here — ``load_reference_learned_dicts``
  unpickles them into attribute-only shim objects and converts each to the
  equivalent registered flax-struct :class:`LearnedDict` pytree.
- ``<i>.pt`` activation chunks: one torch-saved ``[n, d]`` fp16 tensor per
  file (reference: activation_dataset.py:499-503 ``save_activation_chunk``).
  :class:`~sparse_coding_tpu.data.chunk_store.ChunkStore` reads these folders
  directly (format="pt"); ``import_reference_chunks`` converts one to the
  native ``.npy`` store when readahead throughput matters.

Known parity deviations (all from framework-wide row normalization of
exported dictionaries, models/learned_dict.py::normalize_rows):

- reference ``RandomDict`` decodes with its RAW gaussian rows
  (learned_dict.py:114-118); the converted dict normalizes. Feature
  *directions* (MMCS, cosine geometry) are identical.
- reference ``TiedSAE(norm_encoder=False)`` encodes with raw rows; that case
  converts to :class:`UntiedSAE` (raw encoder, normalized decoder), which
  reproduces it exactly.
- reference ``ReverseSAE`` defaults to ``norm_encoder=False`` and its decode
  in-place-mutates the code tensor (learned_dict.py:253-255); the converted
  :class:`ReverseSAE` is the pure normalized-row variant.
- the EXPORT side has the mirror-image deviation (ADVICE r5 #5): a native
  ReverseSAE exports as a reference ``ReverseSAE(norm_encoder=True)``, but
  the reference's own decode (learned_dict.py:246-257) einsums the dict
  TRANSPOSED — correct only for square dictionaries — and mutates its input
  codes in place, so reference-side decode/predict of an exported non-square
  ReverseSAE will not reproduce native decode. Encode-side behavior (the
  part every reference eval driver uses) matches. When reference-side decode
  fidelity matters, export the dict as a plain TiedSAE instead (identical
  encode; standard decode).
"""

from __future__ import annotations

import json
import pickle
from pathlib import Path
from typing import Any

import numpy as np

from sparse_coding_tpu.resilience.atomic import (
    atomic_pickle_dump,
    atomic_save_npy,
    atomic_write_text,
)

_REF_MODULE_PREFIXES = ("autoencoders", "torchtyping", "test_datasets")


class _RefShim:
    """Stand-in for a reference class during unpickling: instances only
    carry the pickled ``__dict__`` (reference classes are plain Python
    objects, so default pickling is class + attribute dict)."""

    def __init__(self, *args, **kwargs):  # tolerate NEWOBJ with args
        pass


_shim_cache: dict[tuple[str, str], type] = {}


def _shim_class(module: str, name: str) -> type:
    key = (module, name)
    if key not in _shim_cache:
        _shim_cache[key] = type(name, (_RefShim,), {"__module__": module})
    return _shim_cache[key]


# The ONLY non-shim globals a reference learned_dicts.pt may reference:
# torch tensor-rebuild machinery, container/scalar plumbing, and numpy
# array reconstruction (hyperparams dicts may carry numpy values). A
# pickle is attacker-controlled code by default (any __reduce__ global
# runs at load), and the serving registry makes untrusted artifacts a live
# ingestion path — so find_class is deny-by-default (ADVICE r5 #1).
_ALLOWED_GLOBALS: dict[str, frozenset[str]] = {
    "collections": frozenset({"OrderedDict", "defaultdict"}),
    "builtins": frozenset({
        "list", "tuple", "dict", "set", "frozenset", "bytearray",
        "int", "float", "bool", "complex", "str", "bytes", "slice",
        "range", "NoneType",
    }),
    "copyreg": frozenset({"_reconstructor"}),
    "numpy": frozenset({
        "ndarray", "dtype", "bool_", "int8", "int16", "int32", "int64",
        "uint8", "uint16", "uint32", "uint64", "float16", "float32",
        "float64", "complex64", "complex128", "longlong", "ulonglong",
    }),
    "numpy.core.multiarray": frozenset({"_reconstruct", "scalar"}),
    "numpy._core.multiarray": frozenset({"_reconstruct", "scalar"}),
    "torch": frozenset({
        "Size", "device", "dtype", "ByteStorage", "DoubleStorage",
        "FloatStorage", "HalfStorage", "LongStorage", "IntStorage",
        "ShortStorage", "CharStorage", "BoolStorage", "BFloat16Storage",
    }),
    "torch.storage": frozenset({"TypedStorage", "UntypedStorage",
                                "_load_from_bytes"}),
    "torch.serialization": frozenset({"_get_layout"}),
}

# Name-prefix rules for modules whose helper set churns across versions:
# torch._utils' tensor-rebuild family (_rebuild_tensor_v2, _rebuild_meta_…)
# all share the _rebuild_ prefix.
_ALLOWED_PREFIXES: dict[str, str] = {"torch._utils": "_rebuild_"}


class _RefUnpickler(pickle.Unpickler):
    """Resolves reference-package globals to shims; torch/numpy/container
    rebuild helpers resolve from the allowlist; EVERYTHING else is
    rejected — loading a learned_dicts.pt must never execute arbitrary
    globals from a crafted pickle."""

    def find_class(self, module: str, name: str):
        if module.split(".")[0] in _REF_MODULE_PREFIXES:
            return _shim_class(module, name)
        prefix = _ALLOWED_PREFIXES.get(module)
        allowed_here = (name in _ALLOWED_GLOBALS.get(module, frozenset())
                        or (prefix is not None
                            and name.startswith(prefix)))
        if not allowed_here:
            raise pickle.UnpicklingError(
                f"refusing to unpickle global {module}.{name}: not in the "
                "reference-artifact allowlist (utils/ref_interop.py "
                "_ALLOWED_GLOBALS). If this is a legitimate reference "
                "artifact, extend the allowlist deliberately.")
        return super().find_class(module, name)


def _restricted_load(fh, **kwargs):
    return _RefUnpickler(fh, **kwargs).load()


def _restricted_loads(data, **kwargs):
    import io

    return _RefUnpickler(io.BytesIO(data), **kwargs).load()


class _RefPickleModule:
    """Duck-typed ``pickle_module`` for torch.load. ALL load surfaces route
    through the allowlisted unpickler — torch's legacy format feeds header
    pickles through ``load``/``loads``, which are attacker-controlled bytes
    too."""

    Unpickler = _RefUnpickler
    load = staticmethod(_restricted_load)
    loads = staticmethod(_restricted_loads)
    # torch.load consults these when re-serializing errors / legacy formats
    dump = staticmethod(pickle.dump)
    dumps = staticmethod(pickle.dumps)
    HIGHEST_PROTOCOL = pickle.HIGHEST_PROTOCOL


def _np(v) -> np.ndarray:
    import torch

    if isinstance(v, torch.Tensor):
        return v.detach().cpu().float().numpy()
    return np.asarray(v, dtype=np.float32)


def _nontrivial(v, identity: np.ndarray) -> np.ndarray | None:
    """None when a centering buffer is (missing or) its do-nothing value —
    keeps converted pytrees as small as the information they carry."""
    if v is None:
        return None
    arr = _np(v)
    if arr.shape == identity.shape and np.allclose(arr, identity):
        return None
    return arr


def _convert_one(obj: Any):
    """Shim object (reference class name + attrs) → native LearnedDict."""
    import jax.numpy as jnp

    from sparse_coding_tpu.models.learned_dict import (
        AddedNoise,
        Identity,
        IdentityPositive,
        IdentityReLU,
        RandomDict,
        ReverseSAE,
        Rotation,
        TiedSAE,
        TopKLearnedDict,
        UntiedSAE,
    )

    name = type(obj).__name__
    d = obj.__dict__

    if name == "Identity":
        return Identity.create(int(d["activation_size"]))
    if name == "IdentityReLU":
        bias = d.get("bias")
        if bias is not None and np.any(_np(bias)):
            raise NotImplementedError(
                "reference IdentityReLU with a non-zero bias has no native "
                "counterpart (the reference constructor cannot actually set "
                "one either — `if bias:` on a tensor raises)")
        return IdentityReLU.create(int(d["activation_size"]))
    if name == "IdentityPositive":
        return IdentityPositive.create(int(d["activation_size"]))
    if name == "RandomDict":
        return RandomDict(dictionary=jnp.asarray(_np(d["encoder"])))
    if name == "Rotation":
        return Rotation(rotation=jnp.asarray(_np(d["matrix"])))
    if name == "AddedNoise":
        import jax

        return AddedNoise.create(jax.random.PRNGKey(0),
                                 int(d["activation_size"]),
                                 float(_np(d["noise_mag"])))
    if name == "UntiedSAE":
        return UntiedSAE(encoder=jnp.asarray(_np(d["encoder"])),
                         encoder_bias=jnp.asarray(_np(d["encoder_bias"])),
                         dictionary=jnp.asarray(_np(d["decoder"])))
    if name in ("TiedSAE", "TiedCenteredSAE"):
        enc = jnp.asarray(_np(d["encoder"]))
        bias = jnp.asarray(_np(d["encoder_bias"]))
        dim = enc.shape[-1]
        rot = _nontrivial(d.get("center_rot"), np.eye(dim, dtype=np.float32))
        trans = _nontrivial(d.get("center_trans"),
                            np.zeros(dim, dtype=np.float32))
        scale = _nontrivial(d.get("center_scale"),
                            np.ones(dim, dtype=np.float32))
        if not d.get("norm_encoder", True):
            if rot is not None or trans is not None or scale is not None:
                raise NotImplementedError(
                    "reference TiedSAE with norm_encoder=False AND a "
                    "non-trivial centering transform is not representable")
            # raw-row encode + normalized decode ≡ native UntiedSAE
            return UntiedSAE(encoder=enc, encoder_bias=bias, dictionary=enc)
        return TiedSAE(
            dictionary=enc, encoder_bias=bias,
            centering_rot=None if rot is None else jnp.asarray(rot),
            centering_trans=None if trans is None else jnp.asarray(trans),
            centering_scale=None if scale is None else jnp.asarray(scale))
    if name == "ReverseSAE":
        return ReverseSAE(dictionary=jnp.asarray(_np(d["encoder"])),
                          encoder_bias=jnp.asarray(_np(d["encoder_bias"])))
    if name == "TopKLearnedDict":
        return TopKLearnedDict(dictionary=jnp.asarray(_np(d["dict"])),
                               k=int(d["sparsity"]))
    if name in ("TiedPositiveSAE", "UntiedPositiveSAE"):
        # reference mlp_tests.py:8-66: encode uses the RAW |encoder| rows
        # (UntiedPositiveSAE computes a normalized copy but its einsum uses
        # self.encoder, and TiedPositiveSAE defaults norm_encoder=False);
        # decode/get_learned_dict is the row-NORMALIZED encoder in both
        # (the decoder attr is never used at inference). That behavior is
        # exactly native UntiedSAE(enc, bias, enc); the constructor already
        # stored |encoder|, so no abs here. The norm_encoder=True tied case
        # is a plain TiedSAE.
        enc = jnp.asarray(_np(d["encoder"]))
        bias = jnp.asarray(_np(d["encoder_bias"]))
        if name == "TiedPositiveSAE" and d.get("norm_encoder", False):
            return TiedSAE(dictionary=enc, encoder_bias=bias)
        return UntiedSAE(encoder=enc, encoder_bias=bias, dictionary=enc)
    if name == "LISTADenoisingSAE":
        from sparse_coding_tpu.models.lista import LISTADenoisingSAE

        p = d["params"]
        return LISTADenoisingSAE(
            decoder=jnp.asarray(_np(p["decoder"])),
            encoder_layers=_stack_layer_list(p["encoder_layers"]))
    if name == "ResidualDenoisingSAE":
        from sparse_coding_tpu.models.lista import ResidualDenoisingSAE

        p = d["params"]
        # the reference constructor reads params["dict"] though its init
        # writes "decoder" (residual_denoising_autoencoder.py:188,142) —
        # accept either key
        dec = p.get("decoder", p.get("dict"))
        return ResidualDenoisingSAE(
            decoder=jnp.asarray(_np(dec)),
            encoder_layers=_stack_layer_list(p["encoder_layers"]),
            encoder_bias=jnp.asarray(_np(p["encoder_bias"])))

    raise NotImplementedError(
        f"no conversion for reference class {name!r} "
        f"(attrs: {sorted(d)}); supported: Identity, IdentityReLU, "
        "IdentityPositive, RandomDict, Rotation, AddedNoise, UntiedSAE, "
        "TiedSAE, TiedCenteredSAE, ReverseSAE, TopKLearnedDict, "
        "TiedPositiveSAE, UntiedPositiveSAE, LISTADenoisingSAE, "
        "ResidualDenoisingSAE")


def _stack_layer_list(layers) -> dict:
    """Reference per-layer param-dict LISTS → this framework's stacked
    [L, ...] trees (models/lista.py stacks for lax.scan)."""
    import jax

    if not layers:
        # n_hidden_layers=0 is constructible in the reference but the
        # stacked-scan format cannot infer leaf shapes from zero layers
        raise NotImplementedError(
            "reference artifact has an empty encoder_layers list "
            "(n_hidden_layers=0); the stacked-scan LISTA format needs at "
            "least one layer")
    converted = [{k: _np(v) for k, v in layer.items()} for layer in layers]
    return jax.tree.map(lambda *xs: jax.numpy.stack(
        [jax.numpy.asarray(x) for x in xs]), *converted)


def _clean_value(v):
    """Hyperparam leaf → plain python/numpy (recursing into containers):
    the export side pickles these for an environment with NO jax, so no
    jax.Array may survive at any nesting depth; the load side uses the
    same coercion for symmetry."""
    if isinstance(v, (bool, int, float, str, type(None))):
        return v  # plain scalars untouched (bool/int must not round-trip
        # via float32)
    if isinstance(v, dict):
        return {k: _clean_value(x) for k, x in v.items()}
    if isinstance(v, (list, tuple)):
        out = [_clean_value(x) for x in v]
        return tuple(out) if isinstance(v, tuple) else out
    try:
        arr = _np(v)
        return arr.item() if arr.size == 1 else arr
    except (TypeError, ValueError):
        return v


def _clean_hyperparams(h: Any) -> dict:
    if not isinstance(h, dict):
        return {"hyperparams": _clean_value(h)}
    return {k: _clean_value(v) for k, v in h.items()}


def load_reference_learned_dicts(path: str | Path) -> list[tuple[Any, dict]]:
    """Load a reference ``learned_dicts.pt`` into native
    ``[(LearnedDict pytree, hyperparams dict), …]`` — the same tuple contract
    :func:`sparse_coding_tpu.utils.artifacts.load_learned_dicts` returns, so
    loaded reference dicts drop straight into every eval/metric driver
    (MMCS/FVU cross-framework comparison, intervention evals, interp)."""
    import torch

    raw = torch.load(str(path), map_location="cpu",
                     pickle_module=_RefPickleModule, weights_only=False)
    if not isinstance(raw, (list, tuple)):
        raise ValueError(f"{path}: expected a list of (dict, hyperparams) "
                         f"tuples, got {type(raw).__name__}")
    out = []
    for item in raw:
        obj, hyper = item if isinstance(item, (list, tuple)) else (item, {})
        out.append((_convert_one(obj), _clean_hyperparams(hyper)))
    return out


def export_reference_learned_dicts(pairs, path: str | Path) -> None:
    """The write side of the interop: save native dicts as a reference
    ``learned_dicts.pt`` that the REFERENCE's own tooling (its plotting /
    interp / eval scripts, which torch.load these pickles) can consume.

    The pickle references ``autoencoders.learned_dict`` classes by
    qualified name — resolved at LOAD time in the reference's environment;
    writing here needs no reference package (shim classes are registered
    for the duration of the save). Exportable natives: UntiedSAE, TiedSAE
    (with optional centering), ReverseSAE, TopKLearnedDict. State layouts
    mirror the reference constructors (learned_dict.py:129-257,
    topk_encoder.py:49-63).

    ReverseSAE caveat: the reference's ReverseSAE.decode is transposed (only
    square dicts) and mutates codes in place, so an exported ReverseSAE
    matches the reference on ENCODE only — see the module docstring; export
    as TiedSAE when reference-side decode must agree."""
    import sys
    import types

    import torch

    from sparse_coding_tpu.models.learned_dict import (
        ReverseSAE,
        TiedSAE,
        TopKLearnedDict,
        UntiedSAE,
    )

    def t(v) -> "torch.Tensor":
        return torch.tensor(np.asarray(jax.device_get(v), np.float32))

    import jax

    def convert(ld):
        if isinstance(ld, UntiedSAE):
            obj = _shim_class("autoencoders.learned_dict", "UntiedSAE")()
            obj.__dict__.update(
                encoder=t(ld.encoder), decoder=t(ld.dictionary),
                encoder_bias=t(ld.encoder_bias))
        elif isinstance(ld, ReverseSAE):
            obj = _shim_class("autoencoders.learned_dict", "ReverseSAE")()
            obj.__dict__.update(encoder=t(ld.dictionary),
                                encoder_bias=t(ld.encoder_bias),
                                norm_encoder=True)
        elif isinstance(ld, TiedSAE):  # after ReverseSAE: not a subclass
            dim = ld.dictionary.shape[-1]
            obj = _shim_class("autoencoders.learned_dict", "TiedSAE")()
            obj.__dict__.update(
                encoder=t(ld.dictionary), encoder_bias=t(ld.encoder_bias),
                norm_encoder=True,
                center_trans=(t(ld.centering_trans)
                              if ld.centering_trans is not None
                              else torch.zeros(dim)),
                center_rot=(t(ld.centering_rot)
                            if ld.centering_rot is not None
                            else torch.eye(dim)),
                center_scale=(t(ld.centering_scale)
                              if ld.centering_scale is not None
                              else torch.ones(dim)))
        elif isinstance(ld, TopKLearnedDict):
            obj = _shim_class("autoencoders.topk_encoder",
                              "TopKLearnedDict")()
            obj.__dict__.update(dict=t(ld.get_learned_dict()),
                                sparsity=int(ld.k))
        else:
            raise NotImplementedError(
                f"no reference-format export for {type(ld).__name__}; "
                "exportable: UntiedSAE, TiedSAE, ReverseSAE, "
                "TopKLearnedDict")
        obj.__dict__.update(
            n_feats=int(ld.n_feats), activation_size=int(ld.activation_size))
        return obj

    # hyperparams must unpickle in the reference env (no jax there):
    # coerce array-likes to plain scalars, the mirror of the load side
    records = [(convert(ld), _clean_hyperparams(dict(hyper)))
               for ld, hyper in pairs]
    # the shim classes must be importable by qualified name while pickle
    # WRITES class references (loading in the reference env resolves the
    # real classes instead). Register ONLY the shims these records use,
    # snapshot any attribute they would shadow (the process may have the
    # real reference package imported — its classes must survive), and
    # restore everything afterwards.
    used = {type(obj) for obj, _ in records}
    sentinel = object()
    created_modules: list[str] = []
    shadowed: list[tuple] = []  # (module_obj, attr_name, prior_value)
    try:
        pkg = sys.modules.get("autoencoders")
        if pkg is None:
            pkg = types.ModuleType("autoencoders")
            sys.modules["autoencoders"] = pkg
            created_modules.append("autoencoders")
        for cls in used:
            module = cls.__module__  # always "autoencoders.<sub>" here
            mod = sys.modules.get(module)
            if mod is None:
                mod = types.ModuleType(module)
                sys.modules[module] = mod
                created_modules.append(module)
            shadowed.append((mod, cls.__name__,
                             getattr(mod, cls.__name__, sentinel)))
            setattr(mod, cls.__name__, cls)
            sub = module.split(".", 1)[1]
            shadowed.append((pkg, sub, getattr(pkg, sub, sentinel)))
            setattr(pkg, sub, mod)
        torch.save(records, str(path))
    finally:
        for mod, attr, prior in reversed(shadowed):
            if prior is sentinel:
                if hasattr(mod, attr):
                    delattr(mod, attr)
            else:
                setattr(mod, attr, prior)
        for module in created_modules:
            sys.modules.pop(module, None)


def read_pt_chunk(path: str | Path, dtype=np.float32) -> np.ndarray:
    """One reference activation chunk (torch-saved [n, d] tensor,
    activation_dataset.py:499-503) as a numpy array."""
    import torch

    t = torch.load(str(path), map_location="cpu", weights_only=True)
    if not isinstance(t, torch.Tensor):
        raise ValueError(f"{path}: expected a tensor, got {type(t).__name__}")
    return t.numpy().astype(dtype, copy=False).reshape(t.shape[0], -1)


def import_reference_chunks(src: str | Path, dst: str | Path,
                            dtype: str = "float16") -> int:
    """Convert a reference chunk folder (``0.pt, 1.pt, …``) into a native
    ``.npy`` ChunkStore at ``dst`` (native readahead works on raw .npy
    files; ChunkStore reads .pt folders directly but without readahead).
    Chunk boundaries are preserved 1:1, so skip_chunks-style cursors keep
    meaning. Returns the number of chunks written."""
    src, dst = Path(src), Path(dst)
    paths = sorted((p for p in src.glob("*.pt") if p.stem.isdigit()),
                   key=lambda p: int(p.stem))
    if not paths:
        raise FileNotFoundError(f"no <i>.pt chunks in {src}")
    dst.mkdir(parents=True, exist_ok=True)
    np_dtype = np.dtype(dtype)
    dim = None
    for i, p in enumerate(paths):
        arr = read_pt_chunk(p, dtype=np_dtype)
        dim = arr.shape[-1] if dim is None else dim
        atomic_save_npy(dst / f"{i}.npy", arr)
    meta = {"activation_dim": int(dim), "dtype": str(np_dtype),
            "n_chunks": len(paths), "centered": False,
            "source": str(src), "format": "pt-import"}
    # meta.json last: its presence certifies a complete imported store
    atomic_write_text(dst / "meta.json", json.dumps(meta, indent=2))
    return len(paths)
