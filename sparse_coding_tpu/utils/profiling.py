"""Tracing / profiling instrumentation.

The reference has none (SURVEY.md §5: progressbar counters only). Here:
- `trace(path)`: context manager around `jax.profiler` for TensorBoard-
  readable device traces of any training region;
- `StepTimer`: wall-clock + throughput (activations/sec) tracking with
  warmup skipping — the north-star metric feed for bench.py and sweep logs;
- `annotate`: named trace regions (shows up in the profiler timeline).
"""

from __future__ import annotations

import contextlib
import time
from pathlib import Path
from typing import Iterator, Optional

import jax


@contextlib.contextmanager
def trace(log_dir: str | Path) -> Iterator[None]:
    """Capture a device trace viewable in TensorBoard/XProf."""
    Path(log_dir).mkdir(parents=True, exist_ok=True)
    jax.profiler.start_trace(str(log_dir))
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named region inside a trace (jax.profiler.TraceAnnotation)."""
    return jax.profiler.TraceAnnotation(name)


class StepTimer:
    """Throughput meter: call `tick(n_items)` once per step; read
    `items_per_sec`. Skips `warmup` steps so compile time doesn't pollute the
    rate; `block_on` forces device sync before timestamps when exact per-step
    walls are needed."""

    def __init__(self, warmup: int = 3):
        self.warmup = warmup
        self.reset()

    def reset(self) -> None:
        self._steps = 0
        self._items = 0
        self._t0: Optional[float] = None
        self.last_dt: Optional[float] = None
        self._last_tick: Optional[float] = None

    def tick(self, n_items: int = 1, block_on=None) -> None:
        if block_on is not None:
            jax.block_until_ready(block_on)
        now = time.perf_counter()
        self._steps += 1
        if self._steps == self.warmup + 1:
            self._t0 = now
        elif self._steps > self.warmup + 1:
            self._items += n_items
            self.last_dt = now - (self._last_tick or now)
        self._last_tick = now

    @property
    def items_per_sec(self) -> float:
        if self._t0 is None or self._last_tick is None or self._items == 0:
            return 0.0
        dt = self._last_tick - self._t0
        return self._items / dt if dt > 0 else 0.0

    @property
    def measured_steps(self) -> int:
        return max(0, self._steps - self.warmup - 1)
