"""Tracing / profiling instrumentation.

The reference has none (SURVEY.md §5: progressbar counters only). Here:
- `trace(path)`: context manager for TensorBoard-readable device traces
  of any training region — a thin alias of the crash-safe managed
  capture (`obs/trace.py`: tmp-then-atomic finalize, counted skip on
  error, guaranteed stop on every exit path);
- `StepTimer`: wall-clock + throughput (activations/sec) tracking with
  warmup skipping — the north-star metric feed for bench.py and sweep logs;
- `annotate`: named trace regions (shows up in the profiler timeline).
"""

from __future__ import annotations

import contextlib
import time
from collections import deque
from pathlib import Path
from typing import Iterator, Optional

import jax


@contextlib.contextmanager
def trace(log_dir: str | Path) -> Iterator[None]:
    """Capture a device trace viewable in TensorBoard/XProf. Managed by
    ``obs.trace.capture``: the artifact appears atomically at ``log_dir``
    on close, and a failed capture is a counted skip, never an error in
    the profiled region."""
    from sparse_coding_tpu.obs import trace as obs_trace

    with obs_trace.capture(log_dir):
        yield


def annotate(name: str):
    """Named region inside a trace (jax.profiler.TraceAnnotation)."""
    return jax.profiler.TraceAnnotation(name)


class StepTimer:
    """Throughput meter: call `tick(n_items)` once per step; read
    `items_per_sec`. Skips `warmup` steps so compile time doesn't pollute the
    rate; `block_on` forces device sync before timestamps when exact per-step
    walls are needed.

    This is the repo's ONE throughput code path: `snapshot()` returns the
    measured window (steps, items, items/sec, total wall, the bounded
    per-step wall list — bench.py builds its median-window estimator from
    it), and `publish()` lands the same numbers in the obs registry so
    sweep logs, bench stderr diagnostics, and `obs.report` all read one
    meter (docs/ARCHITECTURE.md §12)."""

    WINDOW_KEEP = 4096  # bound per-step wall retention on multi-hour sweeps

    def __init__(self, warmup: int = 3):
        self.warmup = warmup
        self.reset()

    def reset(self) -> None:
        self._steps = 0
        self._items = 0
        self._t0: Optional[float] = None
        self.last_dt: Optional[float] = None
        self._last_tick: Optional[float] = None
        self._window_s: deque[float] = deque(maxlen=self.WINDOW_KEEP)

    def tick(self, n_items: int = 1, block_on=None) -> None:
        if block_on is not None:
            jax.block_until_ready(block_on)
        now = time.perf_counter()
        self._steps += 1
        if self._steps == self.warmup + 1:
            self._t0 = now
        elif self._steps > self.warmup + 1:
            self._items += n_items
            self.last_dt = now - (self._last_tick or now)
            self._window_s.append(self.last_dt)
        self._last_tick = now

    @property
    def items_per_sec(self) -> float:
        if self._t0 is None or self._last_tick is None or self._items == 0:
            return 0.0
        dt = self._last_tick - self._t0
        return self._items / dt if dt > 0 else 0.0

    @property
    def measured_steps(self) -> int:
        return max(0, self._steps - self.warmup - 1)

    def snapshot(self) -> dict:
        """The measured window as plain data: ``steps`` / ``items`` /
        ``items_per_sec`` / ``total_wall_s`` plus ``window_s`` (per-step
        walls after warmup, newest-last, bounded at WINDOW_KEEP)."""
        total = (0.0 if self._t0 is None or self._last_tick is None
                 else self._last_tick - self._t0)
        return {"steps": self.measured_steps, "items": self._items,
                "items_per_sec": self.items_per_sec,
                "total_wall_s": total, "window_s": tuple(self._window_s)}

    def publish(self, registry=None, prefix: str = "train") -> dict:
        """Feed the snapshot into the obs registry (gauges
        ``<prefix>.items_per_sec`` / ``.measured_steps`` / ``.wall_s``);
        returns the snapshot so callers log the same numbers they
        published."""
        from sparse_coding_tpu import obs

        reg = registry if registry is not None else obs.get_registry()
        snap = self.snapshot()
        reg.gauge(f"{prefix}.items_per_sec").set(snap["items_per_sec"])
        reg.gauge(f"{prefix}.measured_steps").set(snap["steps"])
        reg.gauge(f"{prefix}.wall_s").set(snap["total_wall_s"])
        return snap
