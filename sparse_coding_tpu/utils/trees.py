"""Pytree stack/unstack helpers for the ensemble axis.

Replaces the reference's `stack_dict`/`unstack_dict`
(reference: autoencoders/ensemble.py:50-66) with jax.tree operations. Stacked
pytrees carry a leading ensemble axis of size N on every leaf; all training
math is vmapped over that axis.
"""

from __future__ import annotations

from typing import Any, Sequence

import jax
import jax.numpy as jnp

Pytree = Any


def stack_trees(trees: Sequence[Pytree]) -> Pytree:
    """Stack a list of structurally-identical pytrees along a new leading axis."""
    if not trees:
        raise ValueError("cannot stack an empty list of pytrees")
    return jax.tree.map(lambda *leaves: jnp.stack(leaves, axis=0), *trees)


def unstack_tree(tree: Pytree) -> list[Pytree]:
    """Invert `stack_trees`: split the leading axis into a list of pytrees."""
    leaves, treedef = jax.tree.flatten(tree)
    if not leaves:
        return []
    n = leaves[0].shape[0]
    return [jax.tree.unflatten(treedef, [leaf[i] for leaf in leaves]) for i in range(n)]


def tree_index(tree: Pytree, i: int) -> Pytree:
    """Select member `i` of a stacked pytree."""
    return jax.tree.map(lambda leaf: leaf[i], tree)


def tree_len(tree: Pytree) -> int:
    """Ensemble size of a stacked pytree (leading-axis length of the first leaf)."""
    leaves = jax.tree.leaves(tree)
    if not leaves:
        return 0
    return int(leaves[0].shape[0])


def tree_bytes(tree: Pytree) -> int:
    """Total bytes across all leaves."""
    return sum(leaf.size * leaf.dtype.itemsize for leaf in jax.tree.leaves(tree))


def tree_cast(tree: Pytree, dtype) -> Pytree:
    """Cast all floating-point leaves to `dtype`."""
    def cast(leaf):
        if jnp.issubdtype(leaf.dtype, jnp.floating):
            return leaf.astype(dtype)
        return leaf
    return jax.tree.map(cast, tree)
