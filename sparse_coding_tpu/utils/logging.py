"""Run metrics logging.

The reference hard-wires wandb with secrets read from secrets.json
(reference: big_sweep.py:310-319). Here the default sink is a local JSONL
file (always works in a zero-egress container); wandb attaches on top when
available and requested.

Since the obs subsystem (docs/ARCHITECTURE.md §12) the file is written
through :class:`sparse_coding_tpu.obs.EventSink` — line-atomic appends on
an owned fd (the old buffered ``open("a")`` handle leaked when callers
forgot ``close()``, and a crash could tear a buffered line in half),
fsync every ``flush_every`` records bounding crash loss, and a
torn-tail-tolerant read contract (``obs.read_events``). Records carry the
run correlation ID when the process runs under the pipeline supervisor.
``MetricsLogger`` is a context manager; ``close()`` stays idempotent.
"""

from __future__ import annotations

import time
from pathlib import Path
from typing import Any, Optional

from sparse_coding_tpu import obs


class MetricsLogger:
    def __init__(self, output_folder: str | Path, use_wandb: bool = False,
                 run_name: str = "run", config: Optional[dict] = None,
                 flush_every: int = 50):
        self.folder = Path(output_folder)
        self.folder.mkdir(parents=True, exist_ok=True)
        self.path = self.folder / "metrics.jsonl"
        # fsync every Nth record: bounds crash-loss of metrics lines while
        # keeping per-log cost off the training loop's critical path
        self._sink = obs.EventSink(self.path, fsync_every=flush_every)
        self.wandb = None
        if use_wandb:
            try:
                import wandb

                self.wandb = wandb.init(project="sparse_coding_tpu",
                                        name=run_name, config=config or {})
            except Exception:
                self.wandb = None  # offline image: silently fall back to JSONL

    def log(self, metrics: dict[str, Any], step: Optional[int] = None) -> None:
        rec = {"ts": time.time(),
               **({"step": step} if step is not None else {}), **metrics}
        run = obs.run_id()
        if run:  # supervised: join the run's correlation scope (§12)
            rec.setdefault("run", run)
        self._sink.emit(rec)
        if self.wandb is not None:
            self.wandb.log(metrics, step=step)

    def flush(self) -> None:
        self._sink.flush()

    def close(self) -> None:
        self._sink.close()
        if self.wandb is not None:
            self.wandb.finish()
            self.wandb = None

    def __enter__(self) -> "MetricsLogger":
        return self

    def __exit__(self, *exc) -> None:
        self.close()


def make_hyperparam_name(hyperparams: dict[str, Any]) -> str:
    """Stable run-name from hyperparams (reference: big_sweep.py:75-83)."""
    parts = []
    for k in sorted(hyperparams):
        v = hyperparams[k]
        if isinstance(v, float):
            parts.append(f"{k}{v:.2e}")
        else:
            parts.append(f"{k}{v}")
    return "_".join(parts)
