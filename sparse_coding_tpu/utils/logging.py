"""Run metrics logging.

The reference hard-wires wandb with secrets read from secrets.json
(reference: big_sweep.py:310-319). Here the default sink is a local JSONL
file (always works in a zero-egress container); wandb attaches on top when
available and requested.
"""

from __future__ import annotations

import json
import time
from pathlib import Path
from typing import Any, Optional


class MetricsLogger:
    def __init__(self, output_folder: str | Path, use_wandb: bool = False,
                 run_name: str = "run", config: Optional[dict] = None):
        self.folder = Path(output_folder)
        self.folder.mkdir(parents=True, exist_ok=True)
        self.path = self.folder / "metrics.jsonl"
        self._fh = self.path.open("a")
        self._writes = 0
        self.wandb = None
        if use_wandb:
            try:
                import wandb

                self.wandb = wandb.init(project="sparse_coding_tpu",
                                        name=run_name, config=config or {})
            except Exception:
                self.wandb = None  # offline image: silently fall back to JSONL

    _FLUSH_EVERY = 50  # bound crash-loss of buffered JSONL records

    def log(self, metrics: dict[str, Any], step: Optional[int] = None) -> None:
        rec = {"ts": time.time(), **({"step": step} if step is not None else {}),
               **metrics}
        self._fh.write(json.dumps(rec, default=float) + "\n")
        self._writes += 1
        if self._writes % self._FLUSH_EVERY == 0:
            self._fh.flush()
        if self.wandb is not None:
            self.wandb.log(metrics, step=step)

    def flush(self) -> None:
        self._fh.flush()

    def close(self) -> None:
        self._fh.flush()
        self._fh.close()
        if self.wandb is not None:
            self.wandb.finish()


def make_hyperparam_name(hyperparams: dict[str, Any]) -> str:
    """Stable run-name from hyperparams (reference: big_sweep.py:75-83)."""
    parts = []
    for k in sorted(hyperparams):
        v = hyperparams[k]
        if isinstance(v, float):
            parts.append(f"{k}{v:.2e}")
        else:
            parts.append(f"{k}{v}")
    return "_".join(parts)
