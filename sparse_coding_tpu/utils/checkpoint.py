"""Checkpoint / resume.

The reference only saves converted `learned_dicts.pt` artifacts at
power-of-two chunk counts (reference: big_sweep.py:378-384) — training state
is serializable (ensemble.py:125-161) but never persisted. Here we checkpoint
the FULL training state (params, buffers, optimizer state, lrs, step, data
cursor, RNG) so sweeps resume exactly (SURVEY.md §5 'Checkpoint / resume').

Format: flax msgpack for the pytree + a JSON sidecar for static metadata.

Hardening (docs/ARCHITECTURE.md §10): every write is tmp+fsync+rename, so
an interrupted save can never leave a truncated file at the target path;
the sidecar records the payload's sha256, and restore verifies it before
deserializing — silent corruption becomes a typed
:class:`~sparse_coding_tpu.resilience.errors.CheckpointCorruptionError`
that `train/sweep.py::resume_sweep_state` falls back from (to the
``ckpt_prev/`` last-good set). Fault sites ``ckpt.save``/``ckpt.restore``
let tests drive both failure paths deterministically.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any, Optional

import jax
import numpy as np
from flax import serialization

from sparse_coding_tpu.ensemble import Ensemble, EnsembleState
from sparse_coding_tpu.resilience.atomic import atomic_write_bytes, atomic_write_text
from sparse_coding_tpu.resilience.errors import CheckpointCorruptionError
from sparse_coding_tpu.resilience.faults import fault_point, register_fault_site
from sparse_coding_tpu.resilience.manifest import bytes_sha256

register_fault_site("ckpt.save",
                    "checkpoint save (msgpack and orbax backends)")
register_fault_site("ckpt.restore",
                    "checkpoint restore (msgpack and orbax backends)")


def save_ensemble(ens: Ensemble, path: str | Path,
                  extra: Optional[dict] = None) -> None:
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    state = jax.device_get(ens.state)
    tree = {"params": state.params, "buffers": state.buffers,
            "opt_state": state.opt_state, "lrs": state.lrs,
            "step": state.step, "live": state.live}
    payload = serialization.to_bytes(tree)
    fault_point("ckpt.save")
    atomic_write_bytes(path, payload)
    meta = {"sig_name": state.sig_name,
            "static_buffers": list(state.static_buffers),
            "payload_sha256": bytes_sha256(payload),
            "payload_bytes": len(payload),
            **(extra or {})}
    # sidecar written last: its digest certifies the payload beside it
    atomic_write_text(path.with_suffix(path.suffix + ".meta.json"),
                      json.dumps(meta, indent=2, default=str))


def restore_ensemble(ens: Ensemble, path: str | Path) -> dict:
    """Restore state in-place into a freshly-constructed, same-shape Ensemble.
    Returns the metadata sidecar (incl. any data-cursor extras). Verifies
    the payload digest when the sidecar carries one; raises
    :class:`CheckpointCorruptionError` on mismatch or a payload that no
    longer deserializes."""
    path = Path(path)
    fault_point("ckpt.restore")
    meta_path = path.with_suffix(path.suffix + ".meta.json")
    meta = json.loads(meta_path.read_text()) if meta_path.exists() else {}
    payload = path.read_bytes()
    want = meta.get("payload_sha256")
    if want is not None and bytes_sha256(payload) != want:
        raise CheckpointCorruptionError(
            path, "payload sha256 does not match the sidecar manifest")
    state = jax.device_get(ens.state)
    template = {"params": state.params, "buffers": state.buffers,
                "opt_state": state.opt_state, "lrs": state.lrs,
                "step": state.step, "live": state.live}
    legacy = {k: v for k, v in template.items() if k != "live"}
    try:
        tree = serialization.from_bytes(template, payload)
    except Exception as first_err:  # msgpack errors are library-specific
        # pre-guardian checkpoint (no live leaf): from_bytes rejects a
        # template key the payload lacks — restore the legacy tree and
        # default every member live, instead of misdiagnosing a perfectly
        # sound old checkpoint as corruption
        try:
            tree = dict(serialization.from_bytes(legacy, payload))
            tree["live"] = state.live
        except Exception:
            raise CheckpointCorruptionError(
                path,
                f"payload does not deserialize: {first_err}") from first_err
    new_state = EnsembleState(
        params=tree["params"], buffers=tree["buffers"],
        opt_state=tree["opt_state"], lrs=tree["lrs"], step=tree["step"],
        live=tree.get("live"),
        static_buffers=state.static_buffers, sig_name=state.sig_name)
    # RUNTIME-OWNED device copies, never zero-copy numpy wraps:
    # from_bytes leaves are numpy views into the msgpack payload, and
    # jnp.asarray/device_put wrap external memory zero-copy on CPU. The
    # restored state is DONATED by the train step, and an executable
    # loaded from the persistent compilation cache retains the
    # input-output aliasing the fresh-compile path drops on CPU —
    # aliasing a donated buffer whose memory jax does not own turns the
    # first step into a use-after-release (inf/nan params, then a heap-
    # corruption segfault; found by the §13 warm-restart chaos matrix).
    # jnp.array (copy=True) materializes each leaf into a jax-allocated
    # buffer; the mesh branch then re-places those owned buffers.
    new_state = jax.tree.map(jax.numpy.array, new_state)
    if ens.mesh is not None:
        from sparse_coding_tpu.ensemble import shard_ensemble_state
        new_state = shard_ensemble_state(new_state, ens.mesh)
    ens.state = new_state
    return meta


def save_pytree(tree: Any, path: str | Path) -> None:
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    payload = serialization.to_bytes(jax.device_get(tree))
    fault_point("ckpt.save")
    atomic_write_bytes(path, payload)
    atomic_write_text(path.with_suffix(path.suffix + ".sha256"),
                      bytes_sha256(payload))


def restore_pytree(template: Any, path: str | Path) -> Any:
    path = Path(path)
    fault_point("ckpt.restore")
    payload = path.read_bytes()
    digest_path = path.with_suffix(path.suffix + ".sha256")
    if digest_path.exists():
        want = digest_path.read_text().strip()
        if bytes_sha256(payload) != want:
            raise CheckpointCorruptionError(
                path, "payload sha256 does not match the .sha256 sidecar")
    return serialization.from_bytes(template, payload)
