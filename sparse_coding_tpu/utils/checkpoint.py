"""Checkpoint / resume.

The reference only saves converted `learned_dicts.pt` artifacts at
power-of-two chunk counts (reference: big_sweep.py:378-384) — training state
is serializable (ensemble.py:125-161) but never persisted. Here we checkpoint
the FULL training state (params, buffers, optimizer state, lrs, step, data
cursor, RNG) so sweeps resume exactly (SURVEY.md §5 'Checkpoint / resume').

Format: flax msgpack for the pytree + a JSON sidecar for static metadata.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Any, Optional

import jax
import numpy as np
from flax import serialization

from sparse_coding_tpu.ensemble import Ensemble, EnsembleState


def save_ensemble(ens: Ensemble, path: str | Path,
                  extra: Optional[dict] = None) -> None:
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    state = jax.device_get(ens.state)
    tree = {"params": state.params, "buffers": state.buffers,
            "opt_state": state.opt_state, "lrs": state.lrs, "step": state.step}
    path.write_bytes(serialization.to_bytes(tree))
    meta = {"sig_name": state.sig_name,
            "static_buffers": list(state.static_buffers),
            **(extra or {})}
    path.with_suffix(path.suffix + ".meta.json").write_text(
        json.dumps(meta, indent=2, default=str))


def restore_ensemble(ens: Ensemble, path: str | Path) -> dict:
    """Restore state in-place into a freshly-constructed, same-shape Ensemble.
    Returns the metadata sidecar (incl. any data-cursor extras)."""
    path = Path(path)
    state = jax.device_get(ens.state)
    template = {"params": state.params, "buffers": state.buffers,
                "opt_state": state.opt_state, "lrs": state.lrs,
                "step": state.step}
    tree = serialization.from_bytes(template, path.read_bytes())
    new_state = EnsembleState(
        params=tree["params"], buffers=tree["buffers"],
        opt_state=tree["opt_state"], lrs=tree["lrs"], step=tree["step"],
        static_buffers=state.static_buffers, sig_name=state.sig_name)
    if ens.mesh is not None:
        from sparse_coding_tpu.ensemble import shard_ensemble_state
        new_state = shard_ensemble_state(new_state, ens.mesh)
    else:
        new_state = jax.tree.map(jax.numpy.asarray, new_state)
    ens.state = new_state
    meta_path = path.with_suffix(path.suffix + ".meta.json")
    return json.loads(meta_path.read_text()) if meta_path.exists() else {}


def save_pytree(tree: Any, path: str | Path) -> None:
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    path.write_bytes(serialization.to_bytes(jax.device_get(tree)))


def restore_pytree(template: Any, path: str | Path) -> Any:
    return serialization.from_bytes(template, Path(path).read_bytes())
