"""Learned-dictionary artifact files.

The reference persists sweeps as `learned_dicts.pt`: a torch-pickled list of
(LearnedDict, hyperparams) tuples (reference: big_sweep.py:378-384,
basic_l1_sweep.py:108-115). Here the same contract is a
`learned_dicts.pkl`: a pickled list of records {cls, fields(numpy), static,
hyperparams}, reconstructed into flax-struct pytrees on load — torch-free and
readable from any host.
"""

from __future__ import annotations

import dataclasses
import pickle
from pathlib import Path
from typing import Any, Sequence

import jax
import numpy as np

ARTIFACT_NAME = "learned_dicts.pkl"


def _dict_registry() -> dict[str, type]:
    """Every LearnedDict class in the package, across all model modules."""
    import sparse_coding_tpu.models as m
    from sparse_coding_tpu.models import direct_coef, ica, lista, nmf, pca, rica, semilinear
    from sparse_coding_tpu.models.learned_dict import LearnedDict
    from sparse_coding_tpu.models.sae import ThresholdingSAE

    reg = {name: getattr(m, name) for name in dir(m)
           if isinstance(getattr(m, name), type)}
    for mod in (direct_coef, ica, lista, nmf, pca, rica, semilinear):
        for name in dir(mod):
            obj = getattr(mod, name)
            if isinstance(obj, type) and issubclass(obj, LearnedDict):
                reg[name] = obj
    reg["ThresholdingSAE"] = ThresholdingSAE
    return reg


def _to_numpy_tree(v):
    return jax.tree.map(lambda leaf: np.asarray(jax.device_get(leaf)), v)


def _to_jax_tree(v):
    return jax.tree.map(jax.numpy.asarray, v)


def save_learned_dicts(dicts: Sequence[tuple[Any, dict]], path: str | Path) -> None:
    """dicts: [(LearnedDict, hyperparams), ...] — the reference's tuple
    contract."""
    records = []
    for d, hyper in dicts:
        fields = {}
        static = {}
        for f in dataclasses.fields(d):
            v = getattr(d, f.name)
            if f.metadata.get("pytree_node", True) and v is not None:
                # pytree-valued fields (e.g. LISTA's stacked encoder_layers
                # dict) are converted leaf-wise, not with a bare np.asarray
                fields[f.name] = _to_numpy_tree(v)
            else:
                static[f.name] = v
        records.append({"cls": type(d).__name__, "fields": fields,
                        "static": static, "hyperparams": dict(hyper)})
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    with path.open("wb") as fh:
        pickle.dump(records, fh)


def load_learned_dicts(path: str | Path) -> list[tuple[Any, dict]]:
    with Path(path).open("rb") as fh:
        records = pickle.load(fh)
    reg = _dict_registry()
    out = []
    for rec in records:
        cls = reg[rec["cls"]]
        kwargs = {k: _to_jax_tree(v) for k, v in rec["fields"].items()}
        kwargs.update(rec["static"])
        out.append((cls(**kwargs), rec["hyperparams"]))
    return out
