"""Learned-dictionary artifact files.

The reference persists sweeps as `learned_dicts.pt`: a torch-pickled list of
(LearnedDict, hyperparams) tuples (reference: big_sweep.py:378-384,
basic_l1_sweep.py:108-115). Here the same contract is a
`learned_dicts.pkl`: a pickled list of records {cls, fields(numpy), static,
hyperparams}, reconstructed into flax-struct pytrees on load — torch-free and
readable from any host.
"""

from __future__ import annotations

import dataclasses
import pickle
from pathlib import Path
from typing import Any, Callable, Optional, Sequence

import jax
import numpy as np

from sparse_coding_tpu.resilience.atomic import atomic_pickle_dump

ARTIFACT_NAME = "learned_dicts.pkl"


def _dict_registry() -> dict[str, type]:
    """Every LearnedDict subclass auto-registers at class-creation time
    (models/learned_dict.py LEARNED_DICT_REGISTRY); importing the defining
    modules here triggers registration for classes living outside
    sparse_coding_tpu.models."""
    import sparse_coding_tpu.models  # noqa: F401  (imports the full zoo)
    import sparse_coding_tpu.train.big_sae  # noqa: F401  (BigSAEDict)
    from sparse_coding_tpu.models.learned_dict import LEARNED_DICT_REGISTRY

    return dict(LEARNED_DICT_REGISTRY)


def _to_numpy_tree(v):
    return jax.tree.map(lambda leaf: np.asarray(jax.device_get(leaf)), v)


def _to_jax_tree(v):
    return jax.tree.map(jax.numpy.asarray, v)


def save_learned_dicts(dicts: Sequence[tuple[Any, dict]], path: str | Path) -> None:
    """dicts: [(LearnedDict, hyperparams), ...] — the reference's tuple
    contract."""
    records = []
    for d, hyper in dicts:
        fields = {}
        static = {}
        for f in dataclasses.fields(d):
            v = getattr(d, f.name)
            if f.metadata.get("pytree_node", True) and v is not None:
                # pytree-valued fields (e.g. LISTA's stacked encoder_layers
                # dict) are converted leaf-wise, not with a bare np.asarray
                fields[f.name] = _to_numpy_tree(v)
            else:
                static[f.name] = v
        records.append({"cls": type(d).__name__, "fields": fields,
                        "static": static, "hyperparams": dict(hyper)})
    path = Path(path)
    path.parent.mkdir(parents=True, exist_ok=True)
    # atomic: sweeps re-save this artifact at every save point while other
    # processes (serving registry, eval steps) may be loading it
    atomic_pickle_dump(path, records)


def load_learned_dicts(path: str | Path,
                       select: Optional[Callable[[dict], bool]] = None,
                       skip_diverged: bool = False,
                       ) -> list[tuple[Any, dict]]:
    """``select(hyperparams) -> bool`` filters records BEFORE their arrays
    are reconstructed as jax trees — a serving registry loading two dicts
    out of a 64-member sweep artifact skips 62 host→device transfers.

    ``skip_diverged=True`` drops members the training guardian quarantined
    (hyperparams tagged ``diverged=True`` by train/guardian.py — their
    dictionaries froze at the last finite pre-divergence step and must not
    enter ensembles, evals, or serving stacks); the default keeps them so
    forensic loads can inspect exactly what the artifact holds."""
    with Path(path).open("rb") as fh:
        records = pickle.load(fh)
    reg = _dict_registry()
    out = []
    for rec in records:
        if skip_diverged and rec["hyperparams"].get("diverged"):
            continue
        if select is not None and not select(rec["hyperparams"]):
            continue
        cls = reg[rec["cls"]]
        kwargs = {k: _to_jax_tree(v) for k, v in rec["fields"].items()}
        kwargs.update(rec["static"])
        out.append((cls(**kwargs), rec["hyperparams"]))
    return out
