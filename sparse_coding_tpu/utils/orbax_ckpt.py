"""Orbax checkpoint backend — the TPU-native alternative to msgpack.

The msgpack path (utils/checkpoint.py) gathers the full training state to
host 0 and serializes it inline, which is fine at small-sweep scale but
wrong for the flagship multi-chip configuration: a big-SAE ensemble's
params + Adam moments are sharded over the mesh, and a gather-then-write
checkpoint (a) materializes the whole state in one host's RAM and (b)
blocks training for the full serialization. This backend keeps the
reference capability (full-state exact resume, SURVEY.md §5; the reference
itself never persists training state — big_sweep.py:378-384 saves only
converted artifacts) but writes the TPU way:

- **sharded**: each host writes exactly its own array shards (OCDBT);
  restore places shards directly back onto the mesh with their recorded
  NamedShardings — no host-side gather or scatter ever happens;
- **async with real overlap**: one orbax ``AsyncCheckpointer`` per target
  path (an AsyncCheckpointer serializes ITS OWN saves — ``save()`` blocks
  on its previous write — so a shared instance would fully serialize a
  multi-ensemble checkpoint round). ``save`` returns once device arrays are
  snapshotted to host buffers; disk writes proceed in background across
  paths concurrently, and training continues. Call ``wait()`` before
  relying on the files (e.g. the sweep's staged-set swap — which the sweep
  defers to the NEXT checkpoint round precisely so the writes overlap a
  full round of training);
- **atomic**: orbax writes to a temp dir and renames on commit, so a crash
  mid-write never leaves a torn checkpoint;
- **multi-host aware**: the orbax save itself is collective (every process
  must call it); the metadata sidecar is written by process 0 only.
  Cross-host barriers around directory swaps are the caller's job
  (train/sweep.py uses sync_global_processes).

Metadata (sig_name, chunks_done, RNG cursor, ...) rides a JSON sidecar next
to the checkpoint directory, mirroring the msgpack backend's contract so
`train/sweep.py::resume_sweep_state` treats both backends uniformly.
"""

from __future__ import annotations

import json
from pathlib import Path
from typing import Optional

import jax

from sparse_coding_tpu.ensemble import Ensemble, EnsembleState
from sparse_coding_tpu.resilience.atomic import atomic_write_text
from sparse_coding_tpu.resilience.faults import fault_point, register_fault_site
from sparse_coding_tpu.resilience.manifest import (
    verify_dir_manifest,
    write_dir_manifest,
)

_SUFFIX = ".orbax"

register_fault_site("ckpt.save",
                    "checkpoint save (msgpack and orbax backends)")
register_fault_site("ckpt.restore",
                    "checkpoint restore (msgpack and orbax backends)")


def _state_tree(state: EnsembleState) -> dict:
    return {"params": state.params, "buffers": state.buffers,
            "opt_state": state.opt_state, "lrs": state.lrs,
            "step": state.step, "live": state.live}


def _meta_path(path: Path) -> Path:
    return path.with_suffix(path.suffix + ".meta.json")


def checkpoint_path(base: Path, name: str) -> Path:
    """Canonical on-disk location for one ensemble's orbax checkpoint —
    train/sweep.py builds both save and resume paths through this."""
    return Path(base) / f"{name}{_SUFFIX}"


class AsyncEnsembleCheckpointer:
    """Async orbax checkpointing for ensemble training state.

    Holds one orbax ``AsyncCheckpointer`` PER TARGET PATH (lazily created,
    reused across checkpoint rounds) so saves to different paths overlap on
    disk; a save to the same path naturally serializes behind that path's
    previous write. Share one instance per training loop and `close()` it
    when done (the sweep does so in a finally block, so no background write
    ever outlives the run and races a resume).
    """

    def __init__(self, use_async: bool = True):
        self._use_async = use_async
        self._ckptrs: dict[str, object] = {}
        # saves whose digest manifest is still owed: manifests can only be
        # written once the async write is durable, so wait() writes them
        self._manifest_pending: set[Path] = set()

    def _ckptr_for(self, path: Path):
        import orbax.checkpoint as ocp

        key = str(path)
        if key not in self._ckptrs:
            self._ckptrs[key] = (
                ocp.AsyncCheckpointer(ocp.StandardCheckpointHandler())
                if self._use_async else ocp.StandardCheckpointer())
        return self._ckptrs[key]

    def save(self, ens: Ensemble, path: str | Path,
             extra: Optional[dict] = None) -> None:
        path = Path(path)
        fault_point("ckpt.save")
        if jax.process_index() == 0:
            path.parent.mkdir(parents=True, exist_ok=True)
        state = ens.state
        # orbax commits via temp-dir rename and refuses to overwrite; a
        # same-path re-save (e.g. re-running a crashed chunk) replaces it
        self._ckptr_for(path).save(path.absolute(), _state_tree(state),
                                   force=True)
        self._manifest_pending.add(path)
        if jax.process_index() == 0:
            meta = {"sig_name": state.sig_name,
                    "static_buffers": list(state.static_buffers),
                    **(extra or {})}
            atomic_write_text(_meta_path(path),
                              json.dumps(meta, indent=2, default=str))

    def restore(self, ens: Ensemble, path: str | Path) -> dict:
        """Restore in-place into a freshly-constructed, same-shape Ensemble
        (same contract as utils/checkpoint.py::restore_ensemble). The
        abstract template is built from the live state, so every array is
        restored straight onto its current device/mesh placement."""
        import orbax.checkpoint as ocp

        path = Path(path)
        fault_point("ckpt.restore")
        self.wait()
        # digest-manifest gate (written by wait() after the save was
        # durable): shard corruption raises CheckpointCorruptionError here
        # instead of surfacing as garbage params mid-training
        verify_dir_manifest(path)
        abstract = jax.tree.map(ocp.utils.to_shape_dtype_struct,
                                _state_tree(ens.state))
        try:
            tree = self._ckptr_for(path).restore(path.absolute(), abstract)
        except Exception:
            # pre-guardian checkpoint (no live leaf): restore the legacy
            # tree and default every member live — a sound old checkpoint
            # must not read as corruption (mirrors utils/checkpoint.py);
            # a genuinely damaged payload fails this retry too and
            # propagates
            legacy = {k: v for k, v in abstract.items() if k != "live"}
            tree = dict(self._ckptr_for(path).restore(path.absolute(),
                                                      legacy))
            tree["live"] = ens.state.live
        ens.state = EnsembleState(
            params=tree["params"], buffers=tree["buffers"],
            opt_state=tree["opt_state"], lrs=tree["lrs"], step=tree["step"],
            live=tree.get("live"),
            static_buffers=ens.state.static_buffers,
            sig_name=ens.state.sig_name)
        meta = _meta_path(path)
        return json.loads(meta.read_text()) if meta.exists() else {}

    def wait(self) -> None:
        """Block until every pending write (across all paths) is durable,
        then stamp each newly-durable checkpoint's digest manifest (the
        ``<path>.manifest.json`` sidecar restore verifies)."""
        for ckptr in self._ckptrs.values():
            wait = getattr(ckptr, "wait_until_finished", None)
            if wait is not None:
                wait()
        if jax.process_index() == 0:
            for path in sorted(self._manifest_pending):
                if path.exists():
                    write_dir_manifest(path)
        self._manifest_pending.clear()

    def close(self) -> None:
        self.wait()
        for ckptr in self._ckptrs.values():
            ckptr.close()
        self._ckptrs.clear()


def save_ensemble_orbax(ens: Ensemble, path: str | Path,
                        extra: Optional[dict] = None) -> None:
    """One-shot synchronous save (module-level convenience mirroring
    utils/checkpoint.py::save_ensemble)."""
    ckptr = AsyncEnsembleCheckpointer(use_async=False)
    try:
        ckptr.save(ens, path, extra)
    finally:
        ckptr.close()


def restore_ensemble_orbax(ens: Ensemble, path: str | Path) -> dict:
    ckptr = AsyncEnsembleCheckpointer(use_async=False)
    try:
        return ckptr.restore(ens, path)
    finally:
        ckptr.close()
