"""Developer/ops utilities: remote sync, S3 transfer, dotdict.

Covers the reference's `utils.py`/`cmdutil.py` surface (reference:
utils.py:30-201 — rsync/ssh sync to rented GPU boxes, S3 upload/download,
`dotdict`). Network calls are all lazy and degrade with clear errors in
zero-egress environments; nothing here is on any training path.
"""

from __future__ import annotations

import json
import subprocess
from pathlib import Path
from typing import Optional, Sequence


class dotdict(dict):
    """Attribute access for dict keys (reference: utils.py:98-119)."""

    __getattr__ = dict.get
    __setattr__ = dict.__setitem__
    __delattr__ = dict.__delitem__


def load_secrets(path: str | Path = "secrets.json") -> dict:
    """Optional credentials file ({'wandb_key', 'aws_access_key_id', ...}).
    Unlike the reference (interpret.py:30-32), never read at import time and
    never required: returns {} when absent."""
    p = Path(path)
    if not p.exists():
        return {}
    return json.loads(p.read_text())


def sync(remote: str, local_dir: str | Path = ".",
         remote_dir: str = "~/sparse_coding_tpu", port: Optional[int] = None,
         excludes: Sequence[str] = (".git", "__pycache__", "activation_data",
                                    "output"),
         dry_run: bool = False) -> list[str]:
    """rsync the working tree to a remote box (reference: utils.py:30-96).
    Returns the argv used (handy for tests/dry runs)."""
    cmd = ["rsync", "-avz", "--delete"]
    for e in excludes:
        cmd += ["--exclude", e]
    if port is not None:
        cmd += ["-e", f"ssh -p {port}"]
    cmd += [str(Path(local_dir)) + "/", f"{remote}:{remote_dir}/"]
    if not dry_run:
        subprocess.run(cmd, check=True)
    return cmd


def copy_models(remote: str, remote_path: str, local_dir: str | Path = "models",
                port: Optional[int] = None, dry_run: bool = False) -> list[str]:
    """Pull trained artifacts back (reference: utils.py copy_models)."""
    Path(local_dir).mkdir(parents=True, exist_ok=True)
    cmd = ["rsync", "-avz"]
    if port is not None:
        cmd += ["-e", f"ssh -p {port}"]
    cmd += [f"{remote}:{remote_path}", str(local_dir) + "/"]
    if not dry_run:
        subprocess.run(cmd, check=True)
    return cmd


def _s3_client(secrets: Optional[dict] = None):
    try:
        import boto3
    except ImportError as e:  # boto3 isn't baked into this image
        raise ImportError("boto3 not installed; S3 transfer unavailable") from e
    secrets = secrets or load_secrets()
    kwargs = {}
    if "aws_access_key_id" in secrets:
        kwargs = dict(aws_access_key_id=secrets["aws_access_key_id"],
                      aws_secret_access_key=secrets["aws_secret_access_key"])
    return boto3.client("s3", **kwargs)


def upload_to_aws(local_path: str | Path, bucket: str,
                  s3_key: Optional[str] = None, secrets: Optional[dict] = None) -> str:
    """(reference: utils.py:128-160 upload_to_aws)."""
    local_path = Path(local_path)
    key = s3_key or local_path.name
    _s3_client(secrets).upload_file(str(local_path), bucket, key)
    return f"s3://{bucket}/{key}"


def download_from_aws(bucket: str, s3_key: str, local_path: str | Path,
                      secrets: Optional[dict] = None) -> Path:
    """(reference: utils.py:162-201 download_from_aws)."""
    local_path = Path(local_path)
    local_path.parent.mkdir(parents=True, exist_ok=True)
    _s3_client(secrets).download_file(bucket, s3_key, str(local_path))
    return local_path
