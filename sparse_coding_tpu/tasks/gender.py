"""Gender-by-name probe dataset preparation.

Same capability as the reference's
`test_datasets/preprocess_gender_dataset.py:15-46`: the UCI gender-by-name
CSV (name, gender, count, probability) filtered to names whose " name"
tokenization has an allowed token length, pickled for the erasure/probe
evals. Also provides the probe-batch builder used with
metrics.core.logistic_regression_auroc.
"""

from __future__ import annotations

import csv
import pickle
from pathlib import Path
from typing import Optional

import numpy as np

NAME_FMT = " {name}"  # leading space, as tokenized mid-sentence


def preprocess_gender_dataset(csv_path: str | Path, tokenizer,
                              min_tok_len: int = 1, max_tok_len: int = 1,
                              out_path: Optional[str | Path] = None):
    """Filter the CSV to names with min≤len(tokens)≤max; returns
    (max_tok_len, entries) and optionally pickles it — the reference's
    gender_dataset.pkl contract."""
    entries = []
    with open(csv_path, newline="") as f:
        reader = csv.reader(f)
        next(reader)  # header
        for entry in reader:
            toks = tokenizer(NAME_FMT.format(name=entry[0]))["input_ids"]
            if min_tok_len <= len(toks) <= max_tok_len:
                entries.append(entry)
    result = (max_tok_len, entries)
    if out_path is not None:
        from sparse_coding_tpu.resilience.atomic import atomic_pickle_dump

        atomic_pickle_dump(out_path, result)
    return result


def load_gender_dataset(pkl_path: str | Path):
    with open(pkl_path, "rb") as f:
        return pickle.load(f)


def gender_probe_arrays(entries: list, tokenizer, n_per_class: Optional[int] = None,
                        seed: int = 0) -> tuple[np.ndarray, np.ndarray]:
    """(token_ids [n], labels [n]) with labels 1=female 0=male, class-balanced
    when n_per_class is set — inputs for the AUROC probes
    (metrics/core.py logistic_regression_auroc / ridge_regression_auroc)."""
    rng = np.random.default_rng(seed)
    by_class: dict[int, list[int]] = {0: [], 1: []}
    for entry in entries:
        name, gender = entry[0], entry[1]
        label = 1 if gender.upper().startswith("F") else 0
        tok = tokenizer(NAME_FMT.format(name=name))["input_ids"][0]
        by_class[label].append(tok)
    if n_per_class is not None:
        for k in by_class:
            idx = rng.permutation(len(by_class[k]))[:n_per_class]
            by_class[k] = [by_class[k][i] for i in idx]
    tokens = np.asarray(by_class[0] + by_class[1], np.int32)
    labels = np.asarray([0] * len(by_class[0]) + [1] * len(by_class[1]), np.int32)
    return tokens, labels
