"""Indirect-object-identification (IOI) probe dataset.

Same capability as the reference's `test_datasets/ioi.py:11-67`: templated
clean/corrupted prompt pairs (ABB→A vs ABA→B), with names/locations/objects
filtered to single tokens under the target tokenizer. Templates and word
lists are this framework's own; the contract (tokenized clean/corrupted
tensors of identical shape) matches the reference.
"""

from __future__ import annotations

import numpy as np

ABB_A_TEMPLATE = ("Afterwards, {name_a} and {name_b} went to the {location}. "
                  "{name_b} handed a {object} to {name_a}")
ABA_B_TEMPLATE = ("Afterwards, {name_a} and {name_b} went to the {location}. "
                  "{name_a} handed a {object} to {name_b}")

CANDIDATE_NAMES = [
    "James", "Mary", "John", "Linda", "Robert", "Susan", "Michael", "Karen",
    "David", "Nancy", "William", "Lisa", "Richard", "Sandra", "Thomas",
    "Sarah", "Charles", "Anna", "Daniel", "Laura", "Matthew", "Emma", "Mark",
    "Helen", "Paul", "Alice", "Steven", "Rachel", "Andrew", "Diane", "Peter",
    "Jack", "Henry", "Frank", "Ruth", "Carol", "Grace", "Alan", "Simon",
    "Kate",
]
CANDIDATE_LOCATIONS = ["park", "store", "school", "office", "beach"]
CANDIDATE_OBJECTS = ["book", "pen", "cup", "ball", "hat", "key"]


def _single_token_filter(tokenizer, words: list[str], label: str,
                         strict: bool) -> list[str]:
    kept = []
    for w in words:
        if len(tokenizer(" " + w)["input_ids"]) == 1:
            kept.append(w)
    if strict and len(kept) < len(words):
        missing = set(words) - set(kept)
        raise ValueError(f"{label} not single tokens: {sorted(missing)}")
    return kept


def generate_ioi_dataset(tokenizer, n_abb_a: int, n_abb_b: int, seed: int = 42
                         ) -> tuple[np.ndarray, np.ndarray]:
    """Returns (clean_tokens, corrupted_tokens), both [n, seq]; names are
    single-token-filtered, locations/objects must all be single tokens
    (mirroring the reference's validation split at ioi.py:21-44)."""
    rng = np.random.default_rng(seed)
    names = _single_token_filter(tokenizer, CANDIDATE_NAMES, "names", strict=False)
    if len(names) < 2:
        raise ValueError("fewer than 2 single-token names under this tokenizer")
    locations = _single_token_filter(tokenizer, CANDIDATE_LOCATIONS,
                                     "locations", strict=True)
    objects = _single_token_filter(tokenizer, CANDIDATE_OBJECTS, "objects",
                                   strict=True)

    clean, corrupted = [], []
    for count, (clean_t, corr_t) in ((n_abb_a, (ABB_A_TEMPLATE, ABA_B_TEMPLATE)),
                                     (n_abb_b, (ABA_B_TEMPLATE, ABB_A_TEMPLATE))):
        for _ in range(count):
            name_a, name_b = rng.choice(names, size=2, replace=False)
            kwargs = dict(name_a=name_a, name_b=name_b,
                          location=rng.choice(locations),
                          object=rng.choice(objects))
            clean.append(clean_t.format(**kwargs))
            corrupted.append(corr_t.format(**kwargs))

    clean_ids = np.asarray(tokenizer(clean)["input_ids"], np.int32)
    corrupted_ids = np.asarray(tokenizer(corrupted)["input_ids"], np.int32)
    return clean_ids, corrupted_ids
