"""Task-feature identification: which dictionary features carry a behavior?

Concrete implementation of the capability the reference only gestures at —
`do_ioi_multiple_layers.sh:4` calls an `ioi_feature_ident.py` that does not
exist in its repo (SURVEY.md §2.6). For each dictionary feature, ablate it
(everywhere) during the task forward pass and measure the change in the task
metric (IOI: logit difference between the correct indirect object and the
repeated-subject distractor at each prompt's final position). Features are
ranked by effect size. The intervened forward is compiled ONCE with the
feature index as a traced argument.
"""

from __future__ import annotations

from typing import Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from sparse_coding_tpu.lm.hooks import tap_name
from sparse_coding_tpu.metrics.intervention import (
    ablate_feature_edit,
    ablate_feature_set_edit,
)
from sparse_coding_tpu.models.learned_dict import LearnedDict

Array = jax.Array


def logit_diff_metric(logits: Array, lengths: Array, target_ids: Array,
                      distractor_ids: Array) -> Array:
    """Mean over prompts of logit[target] − logit[distractor] at the position
    that PREDICTS the answer. `lengths` counts the full prompt INCLUDING the
    answer token (the ioi_counterfact templates end with the indirect
    object), and a causal LM's logits at position p score token p+1 — so the
    name choice is read at lengths−2."""
    idx = jnp.arange(logits.shape[0])
    pred = logits[idx, lengths - 2]  # [n, vocab]
    return jnp.mean(pred[idx, target_ids] - pred[idx, distractor_ids])


def _make_base_metric_fn(params, lm_cfg, forward, tokens, lengths,
                         target_ids, distractor_ids):
    """Jitted un-edited task metric — single home for the base-metric
    program shared by identify_task_features and
    cumulative_ablation_curve (their drops/effects must agree exactly)."""
    @jax.jit
    def base_fn():
        logits, _ = forward(params, tokens, lm_cfg)
        return logit_diff_metric(logits, lengths, target_ids, distractor_ids)

    return base_fn


def identify_task_features(
    params, lm_cfg, model: LearnedDict, layer: int, tokens: np.ndarray,
    lengths: np.ndarray, target_ids: np.ndarray, distractor_ids: np.ndarray,
    layer_loc: str = "residual",
    feature_indices: Optional[Sequence[int]] = None,
    top_m: int = 20, forward=None,
) -> dict:
    """Rank features by how much ablating them moves the task metric.

    Returns {"base_metric", "effects" [n_feats], "ranking" (top_m indices by
    |effect|)} — positive effect = ablating the feature REDUCES task
    performance (the feature supports the behavior)."""
    if forward is None:
        from sparse_coding_tpu.lm.convert import forward_fn
        forward = forward_fn(lm_cfg)
    tap = tap_name(layer, layer_loc)
    tokens = jnp.asarray(tokens)
    lengths = jnp.asarray(lengths)
    target_ids = jnp.asarray(target_ids)
    distractor_ids = jnp.asarray(distractor_ids)
    base_fn = _make_base_metric_fn(params, lm_cfg, forward, tokens, lengths,
                                   target_ids, distractor_ids)

    @jax.jit
    def effects_fn(feat_array):
        # one compiled program, lax.map over features — no per-feature host
        # round-trips (a 16k-feature dict would otherwise serialize 16k syncs)
        def one(feat_idx):
            logits, _ = forward(params, tokens, lm_cfg,
                                edit=(tap, ablate_feature_edit(model, feat_idx)))
            return logit_diff_metric(logits, lengths, target_ids,
                                     distractor_ids)

        return jax.lax.map(one, feat_array)

    base = float(base_fn())
    feats = (np.asarray(list(feature_indices), np.int32)
             if feature_indices is not None
             else np.arange(int(model.n_feats), dtype=np.int32))
    feat_effects = base - np.asarray(effects_fn(jnp.asarray(feats)))
    effects = np.zeros(int(model.n_feats), np.float32)
    effects[feats] = feat_effects

    # rank within the evaluated features only, THEN truncate
    order = feats[np.argsort(-np.abs(feat_effects))]
    ranking = [int(i) for i in order[:top_m]]
    return {"base_metric": base, "effects": effects, "ranking": ranking}


def cumulative_ablation_curve(
    params, lm_cfg, model: LearnedDict, layer: int, tokens: np.ndarray,
    lengths: np.ndarray, target_ids: np.ndarray, distractor_ids: np.ndarray,
    ranking: Sequence[int], layer_loc: str = "residual", forward=None,
    base_metric: Optional[float] = None,
) -> dict:
    """Task-erasure curve: jointly ablate the top-m ranked features for
    m = 1..len(ranking) and measure the task metric at each prefix — does
    removing the identified circuit actually destroy the behavior, and how
    concentrated is it? (The task-probe analogue of the concept-erasure
    curve, metrics/erasure.py::feature_erasure_curve; composes
    identify_task_features' ranking with the set-ablation edit.) One
    compiled program: lax.map over the M cumulative masks.

    Returns {"base_metric", "metrics" [M] (metric with top-m ablated),
    "drops" [M] (base − metric)}. Pass `base_metric` when the caller
    already computed it (identify_task_features does) to skip the
    un-edited forward."""
    if forward is None:
        from sparse_coding_tpu.lm.convert import forward_fn
        forward = forward_fn(lm_cfg)
    tap = tap_name(layer, layer_loc)
    tokens = jnp.asarray(tokens)
    lengths = jnp.asarray(lengths)
    target_ids = jnp.asarray(target_ids)
    distractor_ids = jnp.asarray(distractor_ids)
    ranking = np.asarray(list(ranking), np.int32)
    n_feats = int(model.n_feats)
    # cumulative one-hot prefixes: masks[m] ablates ranking[:m+1]
    masks = np.zeros((len(ranking), n_feats), np.float32)
    for m, feat in enumerate(ranking):
        masks[m:, feat] = 1.0

    @jax.jit
    def curve(mask_stack):
        def one(mask):
            logits, _ = forward(params, tokens, lm_cfg,
                                edit=(tap, ablate_feature_set_edit(model,
                                                                  mask)))
            return logit_diff_metric(logits, lengths, target_ids,
                                     distractor_ids)

        return jax.lax.map(one, mask_stack)

    if base_metric is None:
        base_metric = float(_make_base_metric_fn(
            params, lm_cfg, forward, tokens, lengths, target_ids,
            distractor_ids)())
    metrics = np.asarray(curve(jnp.asarray(masks)))
    return {"base_metric": base_metric, "metrics": metrics,
            "drops": base_metric - metrics}


def run_ioi_feature_ident(params, lm_cfg, model: LearnedDict, layer: int,
                          tokenizer, n_prompts: int = 32,
                          layer_loc: str = "residual", forward=None,
                          family: str = "mixed", seed: int = 0,
                          curve: bool = False, **kwargs) -> dict:
    """End-to-end IOI feature identification (the missing
    ioi_feature_ident.py workflow): build the counterfactual IOI dataset
    (`family` selects any ioi_counterfact.TEMPLATE_FAMILIES bank; "mixed"
    = ABBA+BABA, the reference gen_ioi_dataset's population) and rank this
    dictionary's features by their causal effect on the IOI logit-diff."""
    from sparse_coding_tpu.tasks.ioi_counterfact import (
        gen_ioi_dataset_with_distractors,
    )

    tokens, _, lengths, target_ids, distractor_ids = (
        gen_ioi_dataset_with_distractors(tokenizer, n_prompts,
                                         family=family, seed=seed))
    result = identify_task_features(
        params, lm_cfg, model, layer, tokens, lengths, target_ids,
        distractor_ids, layer_loc=layer_loc, forward=forward, **kwargs)
    if curve:
        # opt-in task-erasure curve over the identified ranking: how much
        # of the behavior the top-m features jointly carry (costs top_m
        # extra intervened forwards)
        result["ablation_curve"] = cumulative_ablation_curve(
            params, lm_cfg, model, layer, tokens, lengths, target_ids,
            distractor_ids, result["ranking"], layer_loc=layer_loc,
            forward=forward, base_metric=result["base_metric"])
    return result
