"""Counterfactual IOI dataset with template families and padded batches.

Same capability as the reference's `test_datasets/ioi_counterfact.py`
(Redwood-derived): BABA/ABBA template families with place/object slot
substitution, counterfactual pairs swapping the indirect object, and padded
token tensors with per-sequence lengths (`gen_ioi_dataset`, reference
:338-373). Template wording here is this framework's own.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from sparse_coding_tpu.tasks.ioi import CANDIDATE_NAMES, _single_token_filter

PLACES = ["garden", "market", "library", "harbor", "square"]
OBJECTS = ["coin", "map", "rose", "kite", "drum"]

# [A]/[B] name slots, [PLACE]/[OBJECT] content slots. BABA ordering: B first.
BABA_TEMPLATES = [
    "Later, [B] and [A] met near the [PLACE], and [B] offered the [OBJECT] to [A]",
    "While [B] and [A] waited at the [PLACE], [B] passed the [OBJECT] to [A]",
    "Once [B] and [A] arrived at the [PLACE], [B] showed the [OBJECT] to [A]",
    "After [B] and [A] left the [PLACE], [B] returned the [OBJECT] to [A]",
]


def _swap_first_clause(template: str) -> str:
    """ABBA variant: swap [A]/[B] in the first clause only (the reference
    builds ABBA from BABA the same way, ioi_counterfact.py:201-213)."""
    cut = template.index(",")
    first, rest = template[:cut], template[cut:]
    first = first.replace("[A]", "[TMP]").replace("[B]", "[A]").replace("[TMP]", "[B]")
    return first + rest


ABBA_TEMPLATES = [_swap_first_clause(t) for t in BABA_TEMPLATES]


@dataclass
class CounterfactPrompt:
    text: str
    counterfact: str  # same prompt with the recipient swapped
    subject: str  # the repeated (subject) name
    indirect_object: str  # the correct completion name


def fill_template(template: str, name_a: str, name_b: str, place: str,
                  obj: str) -> str:
    return (template.replace("[A]", name_a).replace("[B]", name_b)
            .replace("[PLACE]", place).replace("[OBJECT]", obj))


def gen_prompt_counterfact(tokenizer, n_prompts: int, family: str = "baba",
                           seed: int = 0) -> list[CounterfactPrompt]:
    """(reference: gen_prompt_counterfact, ioi_counterfact.py:282-336)."""
    rng = np.random.default_rng(seed)
    names = _single_token_filter(tokenizer, CANDIDATE_NAMES, "names", strict=False)
    templates = BABA_TEMPLATES if family == "baba" else ABBA_TEMPLATES
    prompts = []
    for _ in range(n_prompts):
        name_a, name_b, name_c = rng.choice(names, size=3, replace=False)
        t = templates[rng.integers(len(templates))]
        place = PLACES[rng.integers(len(PLACES))]
        obj = OBJECTS[rng.integers(len(OBJECTS))]
        text = fill_template(t, name_a, name_b, place, obj)
        counterfact = fill_template(t, name_c, name_b, place, obj)
        prompts.append(CounterfactPrompt(text=text, counterfact=counterfact,
                                         subject=name_b,
                                         indirect_object=name_a))
    return prompts


def gen_ioi_dataset(tokenizer, n_prompts: int, family: str = "baba",
                    seed: int = 0, prompts=None):
    """Padded tensors + lengths (reference: gen_ioi_dataset,
    ioi_counterfact.py:338-373). Returns
    (tokens [n, max_len], counterfact_tokens, lengths [n], target_ids [n]).
    Pass precomputed `prompts` to tokenize an existing prompt set."""
    if prompts is None:
        prompts = gen_prompt_counterfact(tokenizer, n_prompts, family, seed)
    tok = [tokenizer(p.text)["input_ids"] for p in prompts]
    ctok = [tokenizer(p.counterfact)["input_ids"] for p in prompts]
    max_len = max(max(map(len, tok)), max(map(len, ctok)))
    pad = getattr(tokenizer, "pad_token_id", None) or 0

    def padded(seqs):
        out = np.full((len(seqs), max_len), pad, np.int32)
        for i, s in enumerate(seqs):
            out[i, :len(s)] = s
        return out

    lengths = np.asarray([len(s) for s in tok], np.int32)
    target_ids = np.asarray(
        [tokenizer(" " + p.indirect_object)["input_ids"][0] for p in prompts],
        np.int32)
    return padded(tok), padded(ctok), lengths, target_ids


def gen_ioi_dataset_with_distractors(tokenizer, n_prompts: int,
                                     family: str = "baba", seed: int = 0):
    """Like gen_ioi_dataset but also returns the subject (repeated-name)
    token ids — the distractor completions the IOI logit-diff metric
    compares against. Prompts are generated ONCE and shared, so the
    distractor ids are aligned by construction."""
    prompts = gen_prompt_counterfact(tokenizer, n_prompts, family, seed)
    tokens, ctokens, lengths, target_ids = gen_ioi_dataset(
        tokenizer, n_prompts, family, seed, prompts=prompts)
    distractor_ids = np.asarray(
        [tokenizer(" " + p.subject)["input_ids"][0] for p in prompts],
        np.int32)
    return tokens, ctokens, lengths, target_ids, distractor_ids
