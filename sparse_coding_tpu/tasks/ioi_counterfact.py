"""Counterfactual IOI dataset with template families and padded batches.

Same capability and distributional breadth as the reference's
`test_datasets/ioi_counterfact.py` (Redwood-derived): a multi-family
template bank — short/long BABA narratives, early/late indirect-object
placements, three-name ABC/BAC controls — with place/object/verb slot
substitution, counterfactual pairs swapping the indirect object, and padded
token tensors with per-sequence lengths (`gen_prompt_counterfact`
reference :282-336, `gen_ioi_dataset` :338-373, template banks :133-236).
All template wording here is this framework's own.

Slot conventions: `[A]` = indirect object (the correct completion, always
the final token), `[B]` = subject (the repeated name), `[C]` = bystander
(three-name families only), `[PLACE]`/`[OBJECT]`/`[VERB]` = content slots.
"""

from __future__ import annotations

from dataclasses import dataclass

import numpy as np

from sparse_coding_tpu.tasks.ioi import CANDIDATE_NAMES, _single_token_filter

PLACES = ["garden", "market", "library", "harbor", "square", "station",
          "bakery", "museum"]
OBJECTS = ["coin", "map", "rose", "kite", "drum", "shell", "ribbon", "bell"]
VERBS = ["offered", "passed", "handed", "carried", "brought"]

# [A]/[B] name slots, [PLACE]/[OBJECT]/[VERB] content slots. BABA ordering:
# the subject [B] is mentioned first.
BABA_TEMPLATES = [
    "Later, [B] and [A] met near the [PLACE], and [B] offered the [OBJECT] to [A]",
    "While [B] and [A] waited at the [PLACE], [B] passed the [OBJECT] to [A]",
    "Once [B] and [A] arrived at the [PLACE], [B] showed the [OBJECT] to [A]",
    "After [B] and [A] left the [PLACE], [B] returned the [OBJECT] to [A]",
    "When [B] and [A] toured the [PLACE], [B] handed the [OBJECT] to [A]",
    "Because [B] and [A] stopped by the [PLACE], [B] brought the [OBJECT] to [A]",
    "Yesterday [B] and [A] walked past the [PLACE], and [B] sold the [OBJECT] to [A]",
    "This morning [B] and [A] opened up the [PLACE], and [B] lent the [OBJECT] to [A]",
    "At noon [B] and [A] reached the [PLACE], where [B] tossed the [OBJECT] to [A]",
    "Before [B] and [A] closed the [PLACE], [B] slid the [OBJECT] to [A]",
    "Whenever [B] and [A] visited the [PLACE], [B] carried the [OBJECT] to [A]",
    "Just as [B] and [A] entered the [PLACE], [B] delivered the [OBJECT] to [A]",
    "Although [B] and [A] disliked the [PLACE], [B] still gave the [OBJECT] to [A]",
    "Since [B] and [A] worked at the [PLACE], [B] mailed the [OBJECT] to [A]",
    "As [B] and [A] crossed the [PLACE], [B] threw the [OBJECT] to [A]",
]

# longer narratives: the same family with a middle clause inserted before
# the second mention of the subject (reference: BABA_LONG_TEMPLATES)
_FILLERS = [
    "after a long day of errands",
    "though the rain had only just stopped",
    "while the evening crowd drifted home",
    "once the last customers had gone",
    "as the streetlights flickered on",
    "despite the noise from the parade",
    "just before the gates were locked",
    "while a band rehearsed nearby",
    "after the morning deliveries were done",
    "though neither had planned to stay",
    "as the fog rolled in from the river",
    "when the bells finished ringing",
    "while the vendors packed their stalls",
    "after waiting out the afternoon heat",
    "once their friends had said goodbye",
]


def _with_filler(template: str, filler: str) -> str:
    """Insert a filler clause at the second-clause boundary (the LAST comma:
    some templates open with a comma-bearing adverbial like 'Later,')."""
    cut = template.rindex(",")
    return template[:cut] + ", " + filler + template[cut:]


BABA_LONG_TEMPLATES = [_with_filler(t, f)
                       for t, f in zip(BABA_TEMPLATES, _FILLERS)]

# indirect object mentioned LATE in the opening clause (reference:
# BABA_LATE_IOS)
BABA_LATE_IOS = [
    "That afternoon [B] lingered at the [PLACE] until [A] arrived, and [B] [VERB] the [OBJECT] to [A]",
    "For an hour [B] paced around the [PLACE] waiting for [A], then [B] [VERB] the [OBJECT] to [A]",
    "All week [B] kept a stall at the [PLACE] hoping to see [A], and [B] [VERB] the [OBJECT] to [A]",
    "By the gate of the [PLACE] [B] finally spotted [A], so [B] [VERB] the [OBJECT] to [A]",
    "Near the steps of the [PLACE] [B] caught up with [A], and [B] [VERB] the [OBJECT] to [A]",
    "Inside the crowded [PLACE] [B] searched until [A] appeared, then [B] [VERB] the [OBJECT] to [A]",
    "From the far end of the [PLACE] [B] waved down [A], and [B] [VERB] the [OBJECT] to [A]",
    "Under the clock at the [PLACE] [B] waited for [A], where [B] [VERB] the [OBJECT] to [A]",
]

# indirect object mentioned FIRST (reference: BABA_EARLY_IOS; the subject
# [B] is still the repeated name)
BABA_EARLY_IOS = [
    "[A] was already at the [PLACE] when [B] walked in, and [B] [VERB] the [OBJECT] to [A]",
    "[A] had been browsing the [PLACE] as [B] arrived, so [B] [VERB] the [OBJECT] to [A]",
    "[A] stood outside the [PLACE] while [B] unlocked it, then [B] [VERB] the [OBJECT] to [A]",
    "[A] called out across the [PLACE] and [B] turned around, and [B] [VERB] the [OBJECT] to [A]",
    "[A] sat by the window of the [PLACE] until [B] showed up, and [B] [VERB] the [OBJECT] to [A]",
    "[A] kept a seat at the [PLACE] for [B] all morning, so [B] [VERB] the [OBJECT] to [A]",
    "[A] left a note at the [PLACE] that [B] found at once, and [B] [VERB] the [OBJECT] to [A]",
    "[A] wandered through the [PLACE] just as [B] closed up, and [B] [VERB] the [OBJECT] to [A]",
]

# three-name controls (reference: ABC_TEMPLATES/BAC_TEMPLATES): [C] is a
# bystander; the completion is still [A]
ABC_TEMPLATES = [
    "Then [A], [B] and [C] shared a bench at the [PLACE], and [B] [VERB] the [OBJECT] to [A]",
    "When [A], [B] and [C] toured the [PLACE] together, [B] [VERB] the [OBJECT] to [A]",
    "After [A], [B] and [C] finished lunch at the [PLACE], [B] [VERB] the [OBJECT] to [A]",
    "While [A], [B] and [C] browsed the [PLACE], [B] [VERB] the [OBJECT] to [A]",
]


def _swap_first_pair(template: str) -> str:
    """ABBA/BAC variant: swap the FIRST occurrences of [A] and [B] (the
    opening-clause mentions), leaving the later subject mention and the
    final completion slot in place. Positional, not comma-based: templates
    may open with comma-bearing adverbials ('Later,'), so cutting at the
    first comma — the reference's approach, ioi_counterfact.py:201-213 —
    would silently no-op on them."""
    ia, ib = template.index("[A]"), template.index("[B]")
    (i1, l1), (i2, l2) = sorted(((ia, "[A]"), (ib, "[B]")))
    return (template[:i1] + l2 + template[i1 + 3:i2] + l1
            + template[i2 + 3:])


ABBA_TEMPLATES = [_swap_first_pair(t) for t in BABA_TEMPLATES]
ABBA_LONG_TEMPLATES = [_swap_first_pair(t) for t in BABA_LONG_TEMPLATES]
ABBA_LATE_IOS = [_swap_first_pair(t) for t in BABA_LATE_IOS]
ABBA_EARLY_IOS = [_swap_first_pair(t) for t in BABA_EARLY_IOS]
BAC_TEMPLATES = [_swap_first_pair(t) for t in ABC_TEMPLATES]

# family name → template bank; "mixed" is the reference gen_ioi_dataset's
# default population (ABBA + BABA, ioi_counterfact.py:345)
TEMPLATE_FAMILIES: dict[str, list[str]] = {
    "baba": BABA_TEMPLATES,
    "abba": ABBA_TEMPLATES,
    "baba_long": BABA_LONG_TEMPLATES,
    "abba_long": ABBA_LONG_TEMPLATES,
    "baba_late": BABA_LATE_IOS,
    "abba_late": ABBA_LATE_IOS,
    "baba_early": BABA_EARLY_IOS,
    "abba_early": ABBA_EARLY_IOS,
    "abc": ABC_TEMPLATES,
    "bac": BAC_TEMPLATES,
    "mixed": ABBA_TEMPLATES + BABA_TEMPLATES,
}


@dataclass
class CounterfactPrompt:
    text: str
    counterfact: str  # same prompt with the recipient swapped
    subject: str  # the repeated (subject) name
    indirect_object: str  # the correct completion name


def fill_template(template: str, name_a: str, name_b: str, place: str,
                  obj: str, name_c: str = "", verb: str = "gave") -> str:
    return (template.replace("[A]", name_a).replace("[B]", name_b)
            .replace("[C]", name_c).replace("[PLACE]", place)
            .replace("[OBJECT]", obj).replace("[VERB]", verb))


def gen_prompt_counterfact(tokenizer, n_prompts: int, family: str = "baba",
                           seed: int = 0) -> list[CounterfactPrompt]:
    """(reference: gen_prompt_counterfact, ioi_counterfact.py:282-336).
    `family` is any key of TEMPLATE_FAMILIES."""
    if family not in TEMPLATE_FAMILIES:
        raise ValueError(f"unknown family {family!r}; one of "
                         f"{sorted(TEMPLATE_FAMILIES)}")
    rng = np.random.default_rng(seed)
    names = _single_token_filter(tokenizer, CANDIDATE_NAMES, "names",
                                 strict=False)
    if len(names) < 4:
        raise ValueError(
            f"fewer than 4 single-token names under this tokenizer "
            f"({len(names)}): counterfact generation draws A/B/bystander/"
            "replacement without replacement")
    templates = TEMPLATE_FAMILIES[family]
    prompts = []
    for _ in range(n_prompts):
        # 4 draws: A (indirect object), B (subject), C (bystander for the
        # three-name families), and the counterfactual replacement for A
        name_a, name_b, name_c, name_cf = rng.choice(names, size=4,
                                                     replace=False)
        t = templates[rng.integers(len(templates))]
        place = PLACES[rng.integers(len(PLACES))]
        obj = OBJECTS[rng.integers(len(OBJECTS))]
        verb = VERBS[rng.integers(len(VERBS))]
        text = fill_template(t, name_a, name_b, place, obj, name_c, verb)
        counterfact = fill_template(t, name_cf, name_b, place, obj, name_c,
                                    verb)
        prompts.append(CounterfactPrompt(text=text, counterfact=counterfact,
                                         subject=name_b,
                                         indirect_object=name_a))
    return prompts


def gen_ioi_dataset(tokenizer, n_prompts: int, family: str = "baba",
                    seed: int = 0, prompts=None):
    """Padded tensors + lengths (reference: gen_ioi_dataset,
    ioi_counterfact.py:338-373). Returns
    (tokens [n, max_len], counterfact_tokens, lengths [n], target_ids [n]).
    Pass precomputed `prompts` to tokenize an existing prompt set."""
    if prompts is None:
        prompts = gen_prompt_counterfact(tokenizer, n_prompts, family, seed)
    tok = [tokenizer(p.text)["input_ids"] for p in prompts]
    ctok = [tokenizer(p.counterfact)["input_ids"] for p in prompts]
    max_len = max(max(map(len, tok)), max(map(len, ctok)))
    pad = getattr(tokenizer, "pad_token_id", None) or 0

    def padded(seqs):
        out = np.full((len(seqs), max_len), pad, np.int32)
        for i, s in enumerate(seqs):
            out[i, :len(s)] = s
        return out

    lengths = np.asarray([len(s) for s in tok], np.int32)
    target_ids = np.asarray(
        [tokenizer(" " + p.indirect_object)["input_ids"][0] for p in prompts],
        np.int32)
    return padded(tok), padded(ctok), lengths, target_ids


def gen_ioi_dataset_with_distractors(tokenizer, n_prompts: int,
                                     family: str = "baba", seed: int = 0):
    """Like gen_ioi_dataset but also returns the subject (repeated-name)
    token ids — the distractor completions the IOI logit-diff metric
    compares against. Prompts are generated ONCE and shared, so the
    distractor ids are aligned by construction."""
    prompts = gen_prompt_counterfact(tokenizer, n_prompts, family, seed)
    tokens, ctokens, lengths, target_ids = gen_ioi_dataset(
        tokenizer, n_prompts, family, seed, prompts=prompts)
    distractor_ids = np.asarray(
        [tokenizer(" " + p.subject)["input_ids"][0] for p in prompts],
        np.int32)
    return tokens, ctokens, lengths, target_ids, distractor_ids
