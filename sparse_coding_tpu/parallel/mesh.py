"""Device-mesh construction and sharding rules.

Single place defining the framework's parallelism model, replacing all three
of the reference's distribution mechanisms (SURVEY.md §2.7):
- process-per-GPU ensemble scheduling (reference: cluster_runs.py:100-157),
- gloo DDP all-reduce (reference: experiments/huge_batch_size.py:337-342),
- manual device lists in experiment fns (big_sweep_experiments.py:51,68).

Axes:
- "model": the ensemble axis — members sharded across chips (the analogue of
  one reference worker process per GPU);
- "data": batch axis — activation slabs sharded across chips, grads reduced
  by XLA psum over ICI.

A very large single SAE (the huge_batch_size.py regime) additionally shards
the feature dimension over "model" — see train/big_sae.py.

Multi-host: `initialize_distributed()` wires `jax.distributed` so the same
mesh spans hosts (ICI within a slice, DCN across; XLA routes collectives).
"""

from __future__ import annotations

import os
from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

MODEL_AXIS = "model"
DATA_AXIS = "data"


def compat_shard_map(fn, mesh: Mesh, in_specs, out_specs):
    """Version-portable ``shard_map`` with replication checking off: newer
    jax exposes ``jax.shard_map(check_vma=...)``, older releases (the
    container's baked toolchain among them) have
    ``jax.experimental.shard_map.shard_map(check_rep=...)``. One home so
    every mesh-composed program (ensemble, big-SAE, sequence-parallel
    forward) builds on either."""
    smap = getattr(jax, "shard_map", None)
    if smap is not None:
        return smap(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                    check_vma=False)
    from jax.experimental.shard_map import shard_map as smap_exp

    return smap_exp(fn, mesh=mesh, in_specs=in_specs, out_specs=out_specs,
                    check_rep=False)


def compat_axis_size(axis_name: str):
    """Version-portable ``jax.lax.axis_size`` (missing on older jax):
    ``psum(1, axis)`` is the portable axis-size idiom — constant-folded
    by XLA, no runtime collective. Call inside shard_map/vmap bodies."""
    if hasattr(jax.lax, "axis_size"):
        return jax.lax.axis_size(axis_name)
    return jax.lax.psum(1, axis_name)


def make_mesh(mesh_model: int = 1, mesh_data: Optional[int] = None,
              devices: Optional[list] = None) -> Mesh:
    """Build a ("model", "data") mesh.

    mesh_data=None uses all remaining devices on the data axis. The model
    axis is placed first so ensemble members land on contiguous devices
    (minimizing ICI hops for the per-member all-reduces, which only span the
    data axis)."""
    devices = devices if devices is not None else jax.devices()
    n = len(devices)
    if mesh_data is None:
        if n % mesh_model != 0:
            raise ValueError(f"{n} devices not divisible by mesh_model={mesh_model}")
        mesh_data = n // mesh_model
    use = mesh_model * mesh_data
    if use > n:
        raise ValueError(f"mesh {mesh_model}x{mesh_data} needs {use} devices, have {n}")
    grid = np.asarray(devices[:use]).reshape(mesh_model, mesh_data)
    return Mesh(grid, (MODEL_AXIS, DATA_AXIS))


def single_device_mesh() -> Mesh:
    return make_mesh(1, 1)


# -- placement helpers (thin aliases over the partition rule layer) ----------
#
# The SINGLE home of "which leaf lives where" is parallel/partition.py
# (docs/ARCHITECTURE.md §19); these wrappers survive for the call sites
# that predate it and DELEGATE so the two modules can never drift.
# Imports are deferred: partition imports this module at load time.


def batch_sharding(mesh: Mesh, stacked: bool = False) -> NamedSharding:
    """Activations [batch, d] — or a [K, batch, d] scan-window stack when
    stacked=True — sharded over the data axis (= partition.batch_sharding)."""
    from sparse_coding_tpu.parallel import partition

    return partition.batch_sharding(mesh, stacked=stacked)


def ensemble_sharding(mesh: Mesh) -> NamedSharding:
    """Stacked ensemble leaves [N, ...] sharded over the model axis
    (= NamedSharding over partition.MEMBER)."""
    from sparse_coding_tpu.parallel import partition

    return NamedSharding(mesh, partition.MEMBER)


def replicated(mesh: Mesh) -> NamedSharding:
    from sparse_coding_tpu.parallel import partition

    return NamedSharding(mesh, partition.REPLICATED)


def feature_sharding(mesh: Mesh) -> NamedSharding:
    """A single giant SAE's [n_feats, d] params sharded over "model" on the
    feature axis — tensor parallelism for the huge_batch_size.py regime
    (= NamedSharding over partition.FEATURE_ROWS)."""
    from sparse_coding_tpu.parallel import partition

    return NamedSharding(mesh, partition.FEATURE_ROWS)


def initialize_distributed(coordinator_address: Optional[str] = None,
                           num_processes: Optional[int] = None,
                           process_id: Optional[int] = None) -> None:
    """Multi-host entry (SURVEY.md §5 'distributed communication backend'):
    call once per host before device queries. No-op when single-process env
    vars are absent and no explicit coordinator is given."""
    if coordinator_address is None and "JAX_COORDINATOR_ADDRESS" not in os.environ:
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
