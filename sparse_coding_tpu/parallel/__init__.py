"""Parallelism helpers: mesh construction (:mod:`parallel.mesh`) and
cross-host consensus.

``agree_any`` is the single home for the any-host-flags-all-hosts-act
agreement rule that multi-host control flow depends on: any branch that
contains collective operations (checkpoint barriers, rollback restores)
must be taken by EVERY host together, or the hosts that skipped it
deadlock the ones inside it. ``train/sweep.py`` uses it for SIGTERM
preemption (the original ``_agree_preempted``) and the training guardian
uses it for anomaly/rollback decisions (train/guardian.py,
docs/ARCHITECTURE.md §16) — one consensus rule, two callers, proven
deadlock-free in tests/test_multihost.py.
"""

from __future__ import annotations

import logging

logger = logging.getLogger(__name__)


def agree_any(flag: bool, tag: str = "") -> bool:
    """Cross-host OR-consensus on a local boolean (identity single-host):
    returns True everywhere iff ANY process passed True. ``tag`` names
    the call site — every multi-host agreement logs it (DEBUG, or WARNING
    when the decision fires), so an operator reading a multi-host hang or
    an unexpected preemption/rollback can tell which agreement was in
    flight; distinct decisions at one boundary must use distinct tags.

    jax is imported lazily so jax-free tools can import the package.
    """
    import jax

    if jax.process_count() == 1:
        return bool(flag)
    import numpy as np
    from jax.experimental import multihost_utils

    flags = multihost_utils.process_allgather(
        np.asarray(bool(flag), dtype=np.bool_))
    agreed = bool(np.any(flags))
    logger.log(logging.WARNING if agreed else logging.DEBUG,
               "agree_any[%s]: local=%s -> global=%s (process %d/%d)",
               tag, bool(flag), agreed, jax.process_index(),
               jax.process_count())
    return agreed


__all__ = ["agree_any"]
