"""Rule-based partition layer (docs/ARCHITECTURE.md §19).

The SINGLE home of "which leaf lives where" for everything that rides
the ("model", "data") mesh. Before this module every mesh consumer
hand-built its own ``NamedSharding``/``PartitionSpec`` table (the
ensemble state placer, big-SAE tensor parallelism, the serving engine),
which is exactly how placement drifts: two call sites disagree about one
leaf and the disagreement is invisible until a resharding collective
shows up in a profile. Now a placement is an ordered **rule set** —
``(regex, PartitionSpec)`` pairs matched against each leaf's
``/``-joined tree path, first match wins, scalars never partitioned
(after the ``match_partition_rules`` idiom, SNIPPETS.md [3]) — and the
named rule sets below are the only placement vocabulary train/serve/data
code may use (analysis rule ``bare-sharding``, §17).

The layer is also the placement *seam* for resilience: every device_put
that moves a tree onto a mesh funnels through :func:`place_tree` and its
named fault site ``partition.place`` (§10), so placement failure — the
transfer path to a sick chip — is drillable like any other I/O edge.

Serving restarts key on :func:`sharding_fingerprint`: the mesh axis
sizes + every leaf's resolved spec, folded into the xcache program key
and warmup-manifest descriptors so a warm mesh restart loads the
mesh-sharded executables instead of recompiling (§13, §19).
"""

from __future__ import annotations

import re
from typing import Any, Optional, Sequence

from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from sparse_coding_tpu.parallel.mesh import DATA_AXIS, MODEL_AXIS
from sparse_coding_tpu.resilience.faults import (
    fault_point,
    register_fault_site,
)

register_fault_site("partition.place",
                    "partition.place_tree — immediately before the "
                    "device_put that moves a pytree onto the mesh per its "
                    "resolved partition rules (the mesh placement seam: "
                    "ensemble state, big-SAE params, serving dict stacks)")

# -- the spec vocabulary ------------------------------------------------------
#
# Named specs for mesh-composed program signatures (shard_map in/out
# specs, ShapeDtypeStruct shardings): train/serve/data code references
# these instead of constructing PartitionSpec literals (bare-sharding).

MEMBER = P(MODEL_AXIS)            # stacked [N, ...] member/ensemble axis
BATCH = P(DATA_AXIS)              # activation rows [B, d]
STACKED_BATCH = P(None, DATA_AXIS)  # [K, B, d] scan-window stacks
REPLICATED = P()
FEATURE_ROWS = P(MODEL_AXIS, None)  # [n, d] feature-axis tensor parallel
FEATURE_COLS = P(None, MODEL_AXIS)  # [d, n] transposed feature sharding

Rules = Sequence[tuple[str, P]]

# -- named rule sets ----------------------------------------------------------

# Stacked ensemble training state (EnsembleState): every leaf carries a
# leading [N] member axis sharded over "model" (each model-shard owns
# N/mesh_model members — the moral equivalent of one reference worker
# process, cluster_runs.py:110-127); scalars (the step counter) replicate
# via the scalar guard in match_partition_rules.
ENSEMBLE_STATE_RULES: Rules = ((r".*", MEMBER),)

# Serving dict stacks (serve/registry.py register_stack): the leading
# stacked-member axis shards over "model", mirroring the training-side
# member placement so ensemble-trained dicts serve where they trained.
SERVE_STACK_RULES: Rules = ((r".*", MEMBER),)

# Single-dict serving entries: replicate — every chip holds the (small)
# dict and the row-sharded batch stays fully data-parallel.
SERVE_REPLICATED_RULES: Rules = ((r".*", REPLICATED),)

# Big-SAE tensor parallelism (train/big_sae.py, the huge_batch_size.py
# regime): the feature axis shards over "model" — dict rows, encoder
# columns, per-feature vectors — and the centering stats replicate.
BIG_SAE_PARAM_RULES: Rules = (
    (r"(^|/)dict$", FEATURE_ROWS),
    (r"(^|/)encoder$", FEATURE_COLS),
    (r"(^|/)threshold$", MEMBER),
    (r"(^|/)centering$", REPLICATED),
)

# Catalog query tensors (catalog/query.py, §20): a big single dict's
# normalized decoder rows [n, d] shard over "model" on the feature axis —
# the same placement the big-SAE dict rows train under, so a catalog
# built from a sharded-training run queries where it trained. (Stacked
# catalog entries need no new rule: SERVE_STACK_RULES already
# member-shards them through the engine's serve_rules path.)
CATALOG_FEATURE_RULES: Rules = ((r".*", FEATURE_ROWS),)

# Full BigSAEState placement: the param rules (also matching the mirrored
# Adam moment leaves by name), per-feature activation totals over
# "model", and a replicated catch-all for the worst-example tracker and
# optimizer tail.
BIG_SAE_STATE_RULES: Rules = BIG_SAE_PARAM_RULES + (
    (r"(^|/)c_totals$", MEMBER),
    (r".*", REPLICATED),
)

# Grouped-sweep ensemble state (Group-SAE, §23): a group tenant's sweep
# is the stacked-ensemble whole-step program over the group's POOLED
# store, so member leaves keep the [N]-over-"model" placement — but the
# pooled-store statistics a grouped run carries (the shared center, any
# per-layer pooling stats) are store-level, not member-level, and
# replicate so every model-shard normalizes pooled rows identically.
GROUP_STATE_RULES: Rules = (
    (r"(^|/)(center|pooled_stats|group_stats)($|/)", REPLICATED),
    (r".*", MEMBER),
)


def batch_spec(stacked: bool = False) -> P:
    """The activation-batch spec: rows over "data" ([B, d], or [K, B, d]
    scan windows when ``stacked``)."""
    return STACKED_BATCH if stacked else BATCH


def serve_rules(is_stack: bool) -> Rules:
    """The rule set for one serving registry entry's pytree."""
    return SERVE_STACK_RULES if is_stack else SERVE_REPLICATED_RULES


# -- rule matching ------------------------------------------------------------


def _key_str(key: Any) -> str:
    """One path entry rendered for rule matching: dict keys and attribute
    names verbatim, sequence/namedtuple positions as digits."""
    for attr in ("key", "name", "idx"):
        if hasattr(key, attr):
            return str(getattr(key, attr))
    return str(key)


def tree_paths(tree: Any) -> list[tuple[str, Any]]:
    """[(path, leaf)] with '/'-joined paths ("params/encoder",
    "opt_state/0/mu/encoder") in flatten order."""
    import jax

    flat, _ = jax.tree_util.tree_flatten_with_path(tree)
    return [("/".join(_key_str(k) for k in path), leaf)
            for path, leaf in flat]


def match_partition_rules(rules: Rules, tree: Any) -> Any:
    """Pytree of PartitionSpec resolved from an ordered rule set
    (SNIPPETS.md [3] ``match_partition_rules``): each leaf's '/'-joined
    path is matched with ``re.search``, first hit wins; 0-d and
    single-element leaves are never partitioned (P()); a leaf no rule
    covers is a hard error — placement must be total, never implicit."""
    import jax

    def spec_for(path: str, leaf: Any) -> P:
        shape = getattr(leaf, "shape", ())
        if len(shape) == 0 or all(int(s) == 1 for s in shape):
            return REPLICATED
        for pattern, spec in rules:
            if re.search(pattern, path) is not None:
                return spec
        raise ValueError(
            f"no partition rule matches leaf {path!r} (shape {shape}); "
            "extend the rule set — placement must be total")

    flat, treedef = jax.tree_util.tree_flatten_with_path(tree)
    specs = [spec_for("/".join(_key_str(k) for k in path), leaf)
             for path, leaf in flat]
    return jax.tree_util.tree_unflatten(treedef, specs)


def tree_shardings(mesh: Mesh, tree: Any, rules: Rules) -> Any:
    """Pytree of NamedSharding over ``mesh`` resolved from ``rules``."""
    import jax

    return jax.tree.map(lambda spec: NamedSharding(mesh, spec),
                        match_partition_rules(rules, tree))


def place_tree(tree: Any, mesh: Mesh, rules: Rules,
               site: str = "partition.place") -> Any:
    """Move a pytree onto the mesh per its resolved rules — THE placement
    seam (§10 fault site ``partition.place``, hit once per placement).
    Leaves are placed one device_put at a time, mirroring the pre-rule
    per-leaf placers this seam replaced — the batched
    ``device_put(tree, shardings)`` form takes a different multi-process
    dispatch path, and placement refactors must never change what
    executes."""
    import jax

    fault_point(site)
    shardings = tree_shardings(mesh, tree, rules)
    return jax.tree.map(lambda leaf, sh: jax.device_put(leaf, sh),
                        tree, shardings)


def place_batch(batch: Any, mesh: Mesh, stacked: bool = False) -> Any:
    """Row-shard one activation slab (or [K, B, d] window stack) over the
    data axis."""
    import jax

    return jax.device_put(batch, NamedSharding(mesh, batch_spec(stacked)))


def batch_sharding(mesh: Mesh, stacked: bool = False) -> NamedSharding:
    """NamedSharding form of :func:`batch_spec` (ShapeDtypeStruct
    shardings for AOT compiles)."""
    return NamedSharding(mesh, batch_spec(stacked))


def sharding_fingerprint(mesh: Optional[Mesh], tree: Any = None,
                         rules: Optional[Rules] = None) -> str:
    """Deterministic string naming one placement: mesh axis sizes plus
    every leaf's resolved spec. Folded into xcache program keys and
    warmup-manifest descriptors (§13) so a mesh-sharded executable and
    its single-device twin never collide, and a warm restart of a mesh
    pool matches exactly the programs it stored."""
    if mesh is None:
        return "unsharded"
    axes = ",".join(f"{name}={size}" for name, size in mesh.shape.items())
    if tree is None or rules is None:
        return f"mesh({axes})"
    paths = tree_paths(match_partition_rules(rules, tree))
    leaves = ";".join(f"{path}:{spec}" for path, spec in paths)
    return f"mesh({axes})|{leaves}"


def batch_alignment(mesh: Optional[Mesh]) -> int:
    """Row alignment the data axis imposes on serving batch shapes: every
    bucket rung must be a multiple of this so row-sharding divides
    evenly (1 when unsharded). The SINGLE home of the divisibility rule —
    engine bucket validation and derived-ladder alignment
    (serve/ladder.py §24) both read it, so a derived rung can never be
    un-shardable on the mesh it will serve on."""
    if mesh is None:
        return 1
    return int(mesh.shape["data"])


__all__ = [
    "MEMBER", "BATCH", "STACKED_BATCH", "REPLICATED",
    "FEATURE_ROWS", "FEATURE_COLS",
    "ENSEMBLE_STATE_RULES", "SERVE_STACK_RULES", "SERVE_REPLICATED_RULES",
    "BIG_SAE_PARAM_RULES", "BIG_SAE_STATE_RULES", "CATALOG_FEATURE_RULES",
    "GROUP_STATE_RULES",
    "batch_spec", "serve_rules", "tree_paths", "match_partition_rules",
    "tree_shardings", "place_tree", "place_batch", "batch_sharding",
    "sharding_fingerprint", "batch_alignment",
]
