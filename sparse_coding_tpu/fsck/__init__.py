"""Whole-tree durable-state audit & repair (docs/ARCHITECTURE.md §22).

Lazy exports (PEP 562) keep ``import sparse_coding_tpu.fsck`` itself
free of numpy/registry imports until a symbol is touched; the full
scan path stays jax-free by contract (tests/test_fsck.py).
"""

from __future__ import annotations

_LAZY_ATTRS = {
    "scan_tree": ("sparse_coding_tpu.fsck.core", "scan_tree"),
    "run_fsck": ("sparse_coding_tpu.fsck.core", "run_fsck"),
    "artifact_roots": ("sparse_coding_tpu.fsck.core", "artifact_roots"),
    "repair_findings": ("sparse_coding_tpu.fsck.repair", "repair_findings"),
    "Finding": ("sparse_coding_tpu.fsck.findings", "Finding"),
    "Report": ("sparse_coding_tpu.fsck.findings", "Report"),
    "FINDING_KINDS": ("sparse_coding_tpu.fsck.findings", "FINDING_KINDS"),
}

__all__ = sorted(_LAZY_ATTRS)


def __getattr__(name: str):
    try:
        mod_name, attr = _LAZY_ATTRS[name]
    except KeyError:
        raise AttributeError(f"module {__name__!r} has no attribute {name!r}")
    import importlib

    return getattr(importlib.import_module(mod_name), attr)


def __dir__():
    return sorted(set(globals()) | set(_LAZY_ATTRS))
