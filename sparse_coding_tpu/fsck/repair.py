"""The provably-safe repair subset: mechanical actions whose safety is
an invariant of the write paths, not a judgment call.

Only findings carrying a ``repair`` action id are touched; everything
else — above all ``INCONSISTENT`` — is an operator decision and repair
REFUSES it by construction (the action table simply has no entry that
could destroy contradictory evidence). Actions:

``debris.sweep``      unlink ``.{name}.tmp.{pid}`` debris (dead owner —
                      the committed file is complete either way)
``lease.drop``        unlink a dead pid's (or unreadable) lease file —
                      the same takeover lease_state() already permits
``journal.trim_tail`` drop the unterminated final line of a JSONL file
                      (strict readers skip it already; trimming makes
                      the lenient ones safe too)
``xcache.drop_entry`` unlink an entry that fails its own header digest,
                      then reconcile the LRU manifest (worst case: one
                      fresh compile)
``xcache.reconcile``  rebuild the LRU manifest deterministically from
                      the entry files (bookkeeping, never ground truth)
``ckpt.drop_staging`` remove ``ckpt_staging/`` leftovers (the resuming
                      sweep discards them anyway)
``ckpt.fallback_prev`` remove a corrupt live ``ckpt/`` set whose
                      ``ckpt_prev/`` fallback verified sound — resume
                      then replays from the last-good set, exactly the
                      path the retention pair exists to provide
``groups.drop_pool``  remove a ``group-<g>/`` pooled-view dir absent
                      from ``groups.json`` (a rebuild at a smaller G
                      leaves stale pools behind); the view holds only a
                      derivable manifest — the chunk bytes live in the
                      shard dirs, untouched

Crash-safety is the same contract as every other durable writer:
``crash_barrier("fsck.repair")`` fires immediately before EACH action's
durable mutation, every action is idempotent (``missing_ok``,
rebuild-compare-skip), and actions apply in sorted order — so SIGKILL
mid-repair, restart, re-run converges on the bitwise-identical repaired
tree (tests/test_pipeline_chaos.py, marker ``chaos``).
"""

from __future__ import annotations

import json
import re
import shutil
from pathlib import Path

from sparse_coding_tpu.fsck.findings import Finding
from sparse_coding_tpu.resilience.atomic import atomic_write_bytes, atomic_write_text
from sparse_coding_tpu.resilience.crash import crash_barrier, register_crash_site

register_crash_site("fsck.repair",
                    "fsck repair engine — immediately before applying one "
                    "repair action's durable mutation (fsck/repair.py); "
                    "SIGKILL here, restart, and the re-run repairs the "
                    "remainder to a bitwise-identical tree")


def _resolve(root: Path, finding: Finding) -> Path:
    p = Path(finding.path)
    return p if p.is_absolute() else root / p


def _unlink(path: Path) -> None:
    path.unlink(missing_ok=True)


def _rmtree(path: Path) -> None:
    shutil.rmtree(path, ignore_errors=True)


def _trim_tail(path: Path) -> None:
    """Keep everything through the last newline; a file with no newline
    at all becomes empty (its only line is the torn one)."""
    try:
        data = path.read_bytes()
    except OSError:
        return
    if not data or data.endswith(b"\n"):
        return
    cut = data.rfind(b"\n")
    kept = data[: cut + 1] if cut >= 0 else b""
    atomic_write_bytes(path, kept)


def _reconcile_manifest(cache_dir: Path) -> None:
    """Deterministic LRU-manifest rebuild from the ``exec/`` directory:
    surviving keys keep their metadata, orphans are adopted with neutral
    metadata and increasing ``last_used`` in sorted-key order, ghosts
    drop. Rebuilding twice (or crashing between) yields identical bytes,
    which is what lets the chaos drill compare repaired trees bitwise."""
    exec_dir = cache_dir / "exec"
    man_path = cache_dir / "manifest.json"
    old = None
    old_entries: dict = {}
    clock = 0
    try:
        old = json.loads(man_path.read_text())
        old_entries = dict(old.get("entries", {}))
        clock = int(old.get("clock", 0))
    except (OSError, ValueError, TypeError, AttributeError):
        old = None
    keys = sorted(p.name[: -len(".bin")] for p in exec_dir.glob("*.bin")) \
        if exec_dir.is_dir() else []
    entries: dict = {}
    for key in keys:
        size = (exec_dir / f"{key}.bin").stat().st_size
        rec = old_entries.get(key)
        if isinstance(rec, dict) and int(rec.get("size", -1)) == size:
            entries[key] = rec
        else:
            clock += 1
            entries[key] = {"size": size, "compile_s": 0.0, "label": "",
                            "last_used": clock}
    if isinstance(old, dict) and old_entries == entries \
            and old.get("clock") == clock:
        return  # already reconciled — idempotent re-run writes nothing
    payload = {"clock": clock, "entries": entries}
    atomic_write_text(man_path, json.dumps(payload, indent=2, sort_keys=True))


_CKPT_SET_RE = re.compile(r"^ckpt(_prev|_staging)?$")


def _ckpt_set_dir(root: Path, finding: Finding, name: str) -> Path | None:
    """Walk up from the finding's path to the checkpoint-set dir called
    ``name`` (findings may point at a file inside the set)."""
    p = _resolve(root, finding)
    for cand in (p, *p.parents):
        if cand.name == name:
            return cand
    return None


def repair_findings(root: str | Path,
                    findings: list[Finding]) -> list[dict]:
    """Apply every finding's named repair action; returns the applied
    action list (sorted, deduped — the report's ``repaired`` field).
    Unknown action ids are skipped loudly in the return value rather
    than raised: a newer scanner must never brick an older repairer."""
    root = Path(root).resolve()
    # dedupe: several findings can demand the same mutation (e.g. every
    # corrupt file in a live ckpt set resolves to one fallback_prev)
    planned: dict[tuple[str, str], Finding] = {}
    for f in findings:
        if not f.repair:
            continue
        target = _resolve(root, f)
        if f.repair == "xcache.drop_entry":
            key = (f.repair, str(target))
        elif f.repair == "xcache.reconcile":
            # findings point either at exec/<key>.bin or at a file in the
            # cache dir itself (manifest.json) — normalize to the cache dir
            cache = (target.parent.parent if target.parent.name == "exec"
                     else target.parent)
            key = (f.repair, str(cache))
        elif f.repair == "ckpt.fallback_prev":
            d = _ckpt_set_dir(root, f, "ckpt")
            if d is None:
                continue
            key = (f.repair, str(d))
        elif f.repair == "ckpt.drop_staging":
            d = _ckpt_set_dir(root, f, "ckpt_staging")
            if d is None:
                continue
            key = (f.repair, str(d))
        else:
            key = (f.repair, str(target))
        planned.setdefault(key, f)

    applied: list[dict] = []
    for (action, target_s), f in sorted(planned.items()):
        target = Path(target_s)
        crash_barrier("fsck.repair")
        if action == "debris.sweep" or action == "lease.drop":
            _unlink(target)
        elif action == "journal.trim_tail":
            _trim_tail(target)
        elif action == "xcache.drop_entry":
            _unlink(target)
            _reconcile_manifest(target.parent.parent)
        elif action == "xcache.reconcile":
            _reconcile_manifest(target)
        elif action == "ckpt.drop_staging" or action == "ckpt.fallback_prev" \
                or action == "groups.drop_pool":
            _rmtree(target)
        else:
            applied.append({"action": action, "path": f.path,
                            "applied": False,
                            "note": "unknown repair action — skipped"})
            continue
        applied.append({"action": action, "path": f.path, "applied": True})
    return sorted(applied, key=lambda a: (a["action"], a["path"]))
