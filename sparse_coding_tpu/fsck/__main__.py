"""``python -m sparse_coding_tpu.fsck <dir> [--repair] [--json]`` — the
cold-state auditor.

Jax-free by contract (tests/test_fsck.py asserts ``'jax' not in
sys.modules`` after a full scan): this is the tool you run against a
wedged-tunnel host (docs/RUNBOOK_TUNNEL.md) where importing jax would
block in the TPU tunnel. Human-readable summary goes to stderr; stdout
is exactly ONE JSON line (bench.py discipline) unless ``--json`` asks
for the full report. Exit status: 0 clean, 1 findings, 2 fatal findings
(a resume over this tree must not proceed).
"""

from __future__ import annotations

import argparse
import json
import sys

from sparse_coding_tpu.fsck.core import run_fsck


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="python -m sparse_coding_tpu.fsck",
        description="Audit (and optionally repair) a run dir or fleet "
                    "tree's durable state.")
    ap.add_argument("root", help="run dir, fleet dir, or any artifact tree")
    ap.add_argument("--repair", action="store_true",
                    help="apply the provably-safe repair subset, then "
                         "re-scan")
    ap.add_argument("--json", action="store_true",
                    help="print the full report JSON to stdout instead of "
                         "the one-line summary")
    ap.add_argument("--stale-after-s", type=float, default=300.0,
                    help="lease staleness window (default: 300)")
    ap.add_argument("--no-report", action="store_true",
                    help="skip writing <root>/fsck/report.json")
    args = ap.parse_args(argv)

    report = run_fsck(args.root, repair=args.repair,
                      write_report=not args.no_report,
                      stale_after_s=args.stale_after_s)

    for f in report.findings:
        mark = "FATAL " if f.fatal else ""
        fix = f" [repair: {f.repair}]" if f.repair else ""
        print(f"{mark}{f.kind:<12} {f.artifact_class:<18} {f.path}: "
              f"{f.detail}{fix}", file=sys.stderr)
    for a in report.repaired:
        print(f"repaired     {a['action']:<18} {a['path']}",
              file=sys.stderr)
    print(f"fsck: {len(report.findings)} finding(s), "
          f"{len(report.fatal)} fatal, {len(report.repaired)} repaired "
          f"under {report.root}", file=sys.stderr)

    if args.json:
        print(report.to_json())
    else:
        print(json.dumps({"findings": len(report.findings),
                          "fatal": len(report.fatal),
                          "repaired": len(report.repaired),
                          "clean": report.clean}, sort_keys=True))
    if report.fatal:
        return 2
    return 0 if report.clean else 1


if __name__ == "__main__":
    sys.exit(main())
