"""Finding taxonomy + the byte-deterministic fsck report.

A finding is one observed defect in the durable tree, typed by what it
MEANS for a resume (docs/ARCHITECTURE.md §22):

``MISSING``
    An artifact a completion marker certifies (a chunk in ``meta.json``,
    a shard in the store manifest, a ``.npy`` in the catalog index) is
    absent. The marker promised completeness, so nothing will regenerate
    it — fatal.
``CORRUPT``
    Damage with a safe fallback or regeneration path: a corrupt xcache
    entry (recompile), a corrupt live checkpoint set with a sound
    ``ckpt_prev/`` retained (the sweep's own fallback), an unreadable
    diagnostic file. Usually repairable.
``TORN``
    An unterminated JSONL tail — the SIGKILL-mid-append instant. Readers
    already skip it by contract (obs/sink.py); the repair trims it so a
    truncated-but-parsing line can never poison a fold.
``ORPHAN``
    Bytes nothing references: ``.tmp.<pid>`` debris from a SIGKILLed
    atomic write (dead owner), xcache entries absent from the LRU
    manifest, ``ckpt_staging/`` leftovers, run dirs absent from the
    fleet queue. Deleting (or adopting) them is provably safe.
``STALE``
    Benign bookkeeping drift: a dead pid's lease, a digest-less legacy
    ledger, a journal "done" whose artifact vanished (the step is
    resumable by contract and simply re-runs).
``INCONSISTENT``
    Two durable artifacts contradict with no safe automatic resolution
    (chunk bytes vs their recorded digest, both checkpoint sets corrupt,
    a seal not matching its manifest, a ledger failing its embedded
    payload digest). Always fatal: a resume over it could silently
    diverge, which is the one outcome fsck exists to forbid.

``fatal=True`` means the supervisor's resume preflight must halt typed
rather than admit work; ``repair`` names the provably-safe action
(fsck/repair.py) or is empty when only an operator can decide.
"""

from __future__ import annotations

import json
from dataclasses import asdict, dataclass, field

MISSING = "MISSING"
CORRUPT = "CORRUPT"
TORN = "TORN"
ORPHAN = "ORPHAN"
STALE = "STALE"
INCONSISTENT = "INCONSISTENT"

FINDING_KINDS = (MISSING, CORRUPT, TORN, ORPHAN, STALE, INCONSISTENT)


@dataclass(frozen=True, order=True)
class Finding:
    """One defect: ``path`` is relative to the scan root where possible
    (posix), absolute otherwise — never host-random, so a report over
    the same tree state is byte-identical."""

    path: str
    artifact_class: str
    kind: str
    detail: str
    repair: str = ""          # repair-action id, "" = not auto-repairable
    fatal: bool = False

    def __post_init__(self):
        if self.kind not in FINDING_KINDS:
            raise ValueError(f"unknown finding kind {self.kind!r}")


@dataclass
class Report:
    """One scan's outcome. ``findings`` are sorted and deduped;
    ``repaired`` lists the actions an immediately-preceding repair pass
    applied (empty for a plain scan)."""

    root: str
    findings: list[Finding] = field(default_factory=list)
    repaired: list[dict] = field(default_factory=list)

    @property
    def fatal(self) -> list[Finding]:
        return [f for f in self.findings if f.fatal]

    @property
    def repairable(self) -> list[Finding]:
        return [f for f in self.findings if f.repair]

    @property
    def clean(self) -> bool:
        return not self.findings

    def counts(self) -> dict[str, int]:
        out: dict[str, int] = {}
        for f in self.findings:
            out[f.kind] = out.get(f.kind, 0) + 1
        return out

    def to_payload(self) -> dict:
        return {
            "version": 1,
            "root": self.root,
            "clean": self.clean,
            "counts": {k: v for k, v in sorted(self.counts().items())},
            "n_fatal": len(self.fatal),
            "findings": [asdict(f) for f in self.findings],
            "repaired": list(self.repaired),
        }

    def to_json(self) -> str:
        # deterministic bytes: sorted findings (dataclass order), sorted
        # keys, no timestamps/pids — two scans of the same tree state
        # produce identical reports, which the chaos matrix compares on
        return json.dumps(self.to_payload(), indent=2, sort_keys=True)


def finalize_findings(findings: list[Finding]) -> list[Finding]:
    """Sorted, deduped finding list (checkers may legitimately observe
    the same defect from two directions, e.g. a shard's meta both as a
    seal mismatch and a store-manifest mismatch)."""
    return sorted(set(findings))
