"""fsck orchestration: walk → checkers → (optional) repair → re-scan →
atomic report.

The walk is deterministic (sorted dirnames and filenames, ``fsck/``
report dirs pruned so a previous report never audits itself) and every
checker sees each directory exactly once — checkers self-select from the
directory's own contents (fsck/checkers.py). A supervisor run dir pulls
its artifact roots in via the persisted ``pipeline.json``
(``_persist_pipeline_config``), so ``run_fsck(<run_dir>)`` audits the
whole durable footprint of the run — journal, leases, chunk store,
checkpoints, eval/catalog outputs, xcache — not just the journal dir.

The report itself is written LAST, atomically, to ``<root>/fsck/
report.json`` (resilience/atomic.py): a crash mid-fsck leaves either the
previous report or none, never a torn one. Report bytes are
deterministic for a given tree state — the chaos drill relies on this
to compare interrupted-then-resumed repairs bitwise.
"""

from __future__ import annotations

import json
import os
from pathlib import Path

from sparse_coding_tpu.fsck.checkers import CHECKERS, REPO_ROOT, ScanCtx
from sparse_coding_tpu.fsck.findings import Report, finalize_findings
from sparse_coding_tpu.fsck.repair import repair_findings
from sparse_coding_tpu.resilience.atomic import atomic_write_text

REPORT_DIR = "fsck"
REPORT_NAME = "report.json"


def _walk_one(ctx: ScanCtx, root: Path) -> None:
    for dirpath, dirnames, filenames in os.walk(root, topdown=True):
        dirnames[:] = sorted(d for d in dirnames if d != REPORT_DIR)
        d = Path(dirpath)
        files, dirs = set(filenames), set(dirnames)
        for check in CHECKERS:
            check(ctx, d, files, dirs)


def scan_tree(root: str | Path, extra_roots=(),
              stale_after_s: float = 300.0) -> Report:
    """Audit ``root`` (plus any ``extra_roots`` not already under it) and
    return the finalized :class:`Report`. Read-only: repair is a
    separate, explicit pass."""
    root = Path(root).resolve()
    ctx = ScanCtx(root=root, stale_after_s=stale_after_s)
    roots = [root]
    for extra in extra_roots:
        extra = Path(extra).resolve()
        if not extra.is_dir():
            continue
        if any(extra == r or r in extra.parents for r in roots):
            continue  # already covered by an earlier root
        roots.append(extra)
    for r in roots:
        _walk_one(ctx, r)
    return Report(root=str(root),
                  findings=finalize_findings(ctx.findings))


def artifact_roots(run_dir: str | Path) -> list[Path]:
    """The artifact directories a supervisor run's persisted
    ``pipeline.json`` names (dataset, sweep output, eval output, catalog
    output), anchored the same way the supervisor anchors them (absolute
    as-is, relative against the repo root)."""
    run_dir = Path(run_dir)
    cfg_path = run_dir / "pipeline.json"
    try:
        config = json.loads(cfg_path.read_text())
    except (OSError, ValueError):
        return []
    if not isinstance(config, dict):
        return []

    def anchor(p) -> Path:
        p = Path(p)
        return p if p.is_absolute() else REPO_ROOT / p

    out: list[Path] = []
    for keys in (("harvest", "dataset_folder"),
                 ("sweep", "ensemble", "output_folder"),
                 ("eval", "output_folder"),
                 ("catalog", "output_folder")):
        node = config
        for k in keys:
            if not isinstance(node, dict) or k not in node:
                node = None
                break
            node = node[k]
        if node is not None:
            out.append(anchor(node))
    return out


def run_fsck(root: str | Path, repair: bool = False,
             write_report: bool = True,
             stale_after_s: float = 300.0) -> Report:
    """The full pass the CLI / supervisor preflight / fleet sweep share:
    scan (a run dir expands to its artifact roots), optionally apply the
    provably-safe repairs and RE-SCAN so the report describes the tree
    as it now is, then atomically write the report last."""
    root = Path(root).resolve()
    extra = artifact_roots(root) if (root / "pipeline.json").exists() else []
    report = scan_tree(root, extra_roots=extra, stale_after_s=stale_after_s)
    if repair and report.repairable:
        applied = repair_findings(root, report.findings)
        report = scan_tree(root, extra_roots=extra,
                           stale_after_s=stale_after_s)
        report.repaired = applied
    if write_report:
        out_dir = root / REPORT_DIR
        out_dir.mkdir(parents=True, exist_ok=True)
        atomic_write_text(out_dir / REPORT_NAME, report.to_json() + "\n")
    return report
