"""Per-artifact-class checkers: the registry the fsck walk drives.

Each checker is ``fn(ctx, d, files, dirs)`` — called once per directory
of the scan (sorted walk order) — and decides from the directory's OWN
contents whether it owns an artifact class there (``meta.json`` with
``chunk_digests`` ⇒ chunk store, ``manifest.json`` with
``kind=sharded_chunk_store`` ⇒ sharded store, ``exec/`` ⇒ xcache,
``index.json`` with ``files`` ⇒ catalog, ``journal.jsonl`` ⇒ supervisor
run dir, ``fleet_queue.jsonl`` ⇒ fleet dir, ``ckpt``/``ckpt_prev`` ⇒
checkpoint retention pair). Verification REUSES the write-side
primitives' rules — ``resilience/manifest.py`` digests, shard seals,
xcache entry self-validation, the obs torn-tail reader contract — plus
the cross-checks no single reader performs (journal "done" ⇒ artifact
exists and verifies; manifest shard count ⇔ sealed dirs; LRU manifest ⇔
directory; catalog index ⇔ ``.npy`` digests; checkpoint sidecars ⇔
``ckpt_prev/`` retention; queue replay ⇔ ``runs/<name>/``).

Every byte read funnels through :meth:`ScanCtx.read_bytes` /
:meth:`ScanCtx.read_quiet` and therefore the named fault site
``fsck.scan`` (tests/test_resilience.py): mode=error degrades the file
to an "unreadable" finding — the scan itself must always complete —
and mode=corrupt flips a read byte so a sound tree reports mismatches
without a single on-disk byte changing.

Import chain is deliberately jax-free (CLI contract, enforced by
tests/test_fsck.py): anything that MIGHT grow a heavy import
(xcache.store, fleet_queue) is imported lazily inside its checker.
"""

from __future__ import annotations

import io
import json
import re
from dataclasses import dataclass, field
from pathlib import Path
from typing import Optional

import numpy as np

from sparse_coding_tpu.fsck.findings import (
    CORRUPT,
    INCONSISTENT,
    MISSING,
    ORPHAN,
    STALE,
    TORN,
    Finding,
)
from sparse_coding_tpu.resilience.faults import fault_point, register_fault_site
from sparse_coding_tpu.resilience.lease import pid_alive, read_lease
from sparse_coding_tpu.resilience.manifest import (
    array_sha256,
    bytes_sha256,
    check_payload_digest,
)

register_fault_site("fsck.scan",
                    "fsck audit read — every artifact byte-read the "
                    "checkers perform (fsck/checkers.py); mode=error "
                    "degrades the file to an 'unreadable' finding, "
                    "mode=corrupt flips a read byte so a sound tree "
                    "reports digest mismatches (scan must still complete)")

# mirrors pipeline/supervisor.py: children run with cwd=REPO_ROOT, so
# relative config paths in pipeline.json anchor against the same root
REPO_ROOT = Path(__file__).resolve().parents[2]

_TMP_RE = re.compile(r"^\..+\.tmp\.(\d+)$")
_SHARD_RE = re.compile(r"^shard-\d+$")
_GROUP_RE = re.compile(r"^group-\d+$")


@dataclass
class ScanCtx:
    """Shared scan state: the root findings are reported relative to,
    the staleness window for lease classification, and the finding
    accumulator every checker appends into."""

    root: Path
    stale_after_s: float = 300.0
    findings: list[Finding] = field(default_factory=list)

    def rel(self, path: Path) -> str:
        try:
            return path.resolve().relative_to(self.root).as_posix() or "."
        except ValueError:
            return path.resolve().as_posix()

    def add(self, path: Path, artifact_class: str, kind: str, detail: str,
            repair: str = "", fatal: bool = False) -> None:
        self.findings.append(Finding(
            path=self.rel(path), artifact_class=artifact_class, kind=kind,
            detail=detail, repair=repair, fatal=fatal))

    def read_quiet(self, path: Path) -> tuple[Optional[bytes], str]:
        """``(bytes, "")`` or ``(None, reason)`` — every checker read
        goes through here so the ``fsck.scan`` fault site covers the
        whole audit surface. The scan NEVER dies over one file."""
        try:
            data = path.read_bytes()
        except OSError as e:
            return None, str(e)
        try:
            return fault_point("fsck.scan", data), ""
        except Exception as e:  # injected error mode (or a torn read)
            return None, str(e)

    def read_bytes(self, path: Path, artifact_class: str) -> Optional[bytes]:
        """read_quiet + an ``unreadable`` CORRUPT finding on failure."""
        data, err = self.read_quiet(path)
        if data is None:
            self.add(path, artifact_class, CORRUPT, f"unreadable: {err}")
        return data


CHECKERS: list = []


def checker(fn):
    CHECKERS.append(fn)
    return fn


def _scan_jsonl(data: bytes) -> tuple[list[dict], int, bool]:
    """The obs event readers' torn-tail contract (obs/sink.py
    scan_events) over in-memory bytes: ``(records, skipped, torn_tail)``
    — only newline-terminated JSON-dict lines count."""
    records: list[dict] = []
    skipped = 0
    if not data:
        return records, skipped, False
    lines = data.split(b"\n")
    torn = bool(lines.pop())
    for line in lines:
        if not line.strip():
            continue
        try:
            rec = json.loads(line)
        except ValueError:
            skipped += 1
            continue
        if isinstance(rec, dict):
            records.append(rec)
        else:
            skipped += 1
    return records, skipped, torn


# -- tmp debris (every directory) ---------------------------------------------

@checker
def check_debris(ctx: ScanCtx, d: Path, files: set, dirs: set) -> None:
    """``.{name}.tmp.{pid}`` files are resilience/atomic.py's staging
    names; one left behind means its writer was SIGKILLed between
    tmp-write and rename. The committed file (old or new) is complete
    either way — the debris is pure orphan bytes once the pid is gone."""
    for name in sorted(files):
        m = _TMP_RE.match(name)
        if not m:
            continue
        pid = int(m.group(1))
        if pid_alive(pid):
            ctx.add(d / name, "debris", STALE,
                    f"atomic-write tmp file owned by live pid {pid} "
                    "(write in flight — not touched)")
        else:
            ctx.add(d / name, "debris", ORPHAN,
                    f"atomic-write tmp debris from dead pid {pid} "
                    "(SIGKILL between tmp-write and rename)",
                    repair="debris.sweep")


# -- chunk stores + their quarantine ledger -----------------------------------

def _quarantined_indices(ctx: ScanCtx, d: Path, files: set) -> set:
    """Indices the quarantine ledger holes out of the store — verified
    first, because a LYING ledger would make fsck mis-read every hole."""
    if "quarantine.json" not in files:
        return set()
    path = d / "quarantine.json"
    data = ctx.read_bytes(path, "quarantine_ledger")
    if data is None:
        return set()
    try:
        raw = json.loads(data)
        chunks = {int(k) for k in raw.get("chunks", {})}
    except (ValueError, TypeError, AttributeError) as e:
        # readers degrade to an empty ledger (data/ledger.py) and the
        # chunk digests still catch what it knew — flagged, not fatal
        ctx.add(path, "quarantine_ledger", CORRUPT,
                f"unparseable quarantine ledger: {e} (readers treat as "
                "empty; quarantined chunks will re-verify as corrupt)")
        return set()
    state = check_payload_digest(raw)
    if state == "mismatch":
        ctx.add(path, "quarantine_ledger", INCONSISTENT,
                "payload digest mismatch — the recorded quarantine set "
                "cannot be trusted (LedgerCorruptionError on load)",
                fatal=True)
    elif state == "absent":
        ctx.add(path, "quarantine_ledger", STALE,
                "digest-less legacy ledger (loads unverified; rewritten "
                "with a digest on its next update)")
    return chunks


@checker
def check_chunk_store(ctx: ScanCtx, d: Path, files: set, dirs: set) -> None:
    """``meta.json`` with ``chunk_digests`` is the completion marker the
    writer emits LAST — so every chunk it certifies must exist and match
    its recorded digest (data/chunk_store.py's read-side rule, applied
    store-wide). Quarantined indices are positional holes by design."""
    if "meta.json" not in files:
        return
    path = d / "meta.json"
    data = ctx.read_bytes(path, "chunk_store")
    if data is None:
        return
    try:
        meta = json.loads(data)
        digests = meta.get("chunk_digests")
    except (ValueError, AttributeError) as e:
        ctx.add(path, "chunk_store", CORRUPT,
                f"unparseable completion marker meta.json: {e}", fatal=True)
        return
    if not isinstance(digests, dict):
        return  # some other subsystem's meta.json
    quarantined = _quarantined_indices(ctx, d, files)
    try:
        n_chunks = int(meta.get("n_chunks", len(digests)))
    except (TypeError, ValueError):
        ctx.add(path, "chunk_store", INCONSISTENT,
                "meta.json n_chunks is not an integer", fatal=True)
        return
    for i in range(n_chunks):
        p = d / f"{i}.npy"
        if i in quarantined:
            continue  # a PR-8 ledger hole, not a defect
        if not p.exists():
            ctx.add(p, "chunk_store", MISSING,
                    "chunk certified complete by meta.json is absent "
                    "(and not quarantined)", fatal=True)
            continue
        want = digests.get(str(i))
        if not want:
            continue  # digest-less legacy chunk — nothing to verify
        raw = ctx.read_bytes(p, "chunk_store")
        if raw is None:
            continue
        try:
            arr = np.load(io.BytesIO(raw), allow_pickle=False)
        except Exception as e:
            ctx.add(p, "chunk_store", INCONSISTENT,
                    f"chunk does not deserialize: {e}", fatal=True)
            continue
        if array_sha256(arr) != want:
            ctx.add(p, "chunk_store", INCONSISTENT,
                    "chunk bytes do not match the digest meta.json "
                    "recorded at finalize", fatal=True)
    for p in sorted(d.glob("*.npy")):
        if p.stem.isdigit() and int(p.stem) >= n_chunks:
            ctx.add(p, "chunk_store", ORPHAN,
                    "chunk file beyond meta.json's n_chunks (nothing "
                    "references it)")


# -- sharded store manifest ⇔ seals -------------------------------------------

@checker
def check_shard_store(ctx: ScanCtx, d: Path, files: set, dirs: set) -> None:
    """Store ``manifest.json`` (written last, after every shard sealed)
    ⇔ the sealed shard dirs: count, per-shard ``meta.json`` digest, and
    the ``shard.digest`` seal must agree three ways
    (data/shard_store.py's build-time rules, re-checked cold)."""
    if "manifest.json" not in files or "exec" in dirs:
        return  # `exec/` means the manifest.json is the xcache's
    path = d / "manifest.json"
    data = ctx.read_bytes(path, "shard_store")
    if data is None:
        return
    try:
        manifest = json.loads(data)
    except ValueError as e:
        if any(_SHARD_RE.match(n) for n in dirs):
            ctx.add(path, "shard_store", CORRUPT,
                    f"unparseable store manifest next to shard dirs: {e}",
                    fatal=True)
        return
    if not isinstance(manifest, dict) \
            or manifest.get("kind") != "sharded_chunk_store":
        return
    shards = manifest.get("shards", [])
    if int(manifest.get("n_shards", -1)) != len(shards):
        ctx.add(path, "shard_store", INCONSISTENT,
                f"manifest n_shards={manifest.get('n_shards')} does not "
                f"match its own shard list ({len(shards)})", fatal=True)
    listed = set()
    for s in shards:
        name = str(s.get("name", ""))
        listed.add(name)
        sd = d / name
        if not sd.is_dir():
            ctx.add(sd, "shard_store", MISSING,
                    "shard listed in the store manifest is absent",
                    fatal=True)
            continue
        meta_p, seal_p = sd / "meta.json", sd / "shard.digest"
        if not meta_p.exists() or not seal_p.exists():
            ctx.add(sd, "shard_store", INCONSISTENT,
                    "manifest lists an unsealed shard (meta.json or "
                    "shard.digest missing)", fatal=True)
            continue
        meta_bytes = ctx.read_bytes(meta_p, "shard_store")
        seal_bytes = ctx.read_bytes(seal_p, "shard_store")
        if meta_bytes is None or seal_bytes is None:
            continue
        got = bytes_sha256(meta_bytes)
        try:
            seal = str(json.loads(seal_bytes)["meta_sha256"])
        except (ValueError, KeyError, TypeError) as e:
            ctx.add(seal_p, "shard_store", INCONSISTENT,
                    f"unreadable shard seal: {e}", fatal=True)
            continue
        if got != seal or got != str(s.get("meta_sha256", "")):
            ctx.add(sd, "shard_store", INCONSISTENT,
                    "shard meta.json digest disagrees with its seal "
                    "and/or the store manifest", fatal=True)
    for name in sorted(dirs):
        if _SHARD_RE.match(name) and name not in listed:
            ctx.add(d / name, "shard_store", ORPHAN,
                    "shard dir absent from the store manifest")


# -- checkpoint retention pair ------------------------------------------------

def _ckpt_set_problems(ctx: ScanCtx, d: Path) -> list[tuple[Path, str]]:
    """Damage list for one checkpoint set dir: msgpack payloads against
    their ``.meta.json`` sidecars (utils/checkpoint.py save_ensemble),
    ``.sha256``-sidecar'd pytrees, and manifest-sidecar'd backend dirs
    (resilience/manifest.py verify_dir_manifest)."""
    problems: list[tuple[Path, str]] = []
    payloads = sorted(d.glob("*.msgpack"))
    if not any(d.iterdir()):
        return [(d, "empty checkpoint set")]
    for p in payloads:
        side = d / (p.name + ".meta.json")
        if not side.exists():
            problems.append((p, "digest sidecar (.meta.json) missing"))
            continue
        side_bytes = ctx.read_quiet(side)[0]
        raw = ctx.read_quiet(p)[0]
        if side_bytes is None or raw is None:
            problems.append((p, "payload or sidecar unreadable"))
            continue
        try:
            want = json.loads(side_bytes)["payload_sha256"]
        except (ValueError, KeyError, TypeError) as e:
            problems.append((side, f"unreadable sidecar: {e}"))
            continue
        if bytes_sha256(raw) != want:
            problems.append((p, "payload does not match its sidecar "
                                "digest"))
    for side in sorted(d.glob("*.sha256")):
        p = d / side.name[:-len(".sha256")]
        if not p.exists():
            problems.append((side, "digest sidecar with no payload"))
            continue
        raw = ctx.read_quiet(p)[0]
        want = (ctx.read_quiet(side)[0] or b"").decode(errors="replace")
        if raw is None or bytes_sha256(raw) != want.strip():
            problems.append((p, "payload does not match its .sha256 "
                                "sidecar"))
    for sub in sorted(x for x in d.iterdir() if x.is_dir()):
        if (d / (sub.name + ".manifest.json")).exists():
            from sparse_coding_tpu.resilience.errors import (
                CheckpointCorruptionError,
            )
            from sparse_coding_tpu.resilience.manifest import (
                verify_dir_manifest,
            )
            try:
                verify_dir_manifest(sub)
            except CheckpointCorruptionError as e:
                problems.append((sub, f"dir manifest verification "
                                      f"failed: {e.reason}"))
    return problems


@checker
def check_checkpoints(ctx: ScanCtx, d: Path, files: set, dirs: set) -> None:
    """The retention invariant (train/sweep.py): ``ckpt/`` is the live
    set, ``ckpt_prev/`` the retained last-good fallback, ``ckpt_staging/``
    transient. Classification depends on BOTH sets and on whether the
    sweep already completed (a ``final/`` artifact): after completion the
    sets are dormant — damage is unregenerable and fatal; before it, a
    corrupt live set with a sound fallback is exactly what the fallback
    exists for (repair: drop the live set, resume replays from prev)."""
    if not ({"ckpt", "ckpt_prev", "ckpt_staging"} & dirs):
        return
    final_done = ("final" in dirs
                  and any((d / "final").glob("*.pkl")))
    if "ckpt_staging" in dirs:
        ctx.add(d / "ckpt_staging", "checkpoint", ORPHAN,
                "staging leftovers from an interrupted checkpoint swap "
                "(the resuming sweep discards them)",
                repair="ckpt.drop_staging")
    live = _ckpt_set_problems(ctx, d / "ckpt") if "ckpt" in dirs else None
    prev = (_ckpt_set_problems(ctx, d / "ckpt_prev")
            if "ckpt_prev" in dirs else None)
    for probs, which in ((live, "ckpt"), (prev, "ckpt_prev")):
        if not probs:
            continue
        for path, why in probs:
            if final_done:
                ctx.add(path, "checkpoint", INCONSISTENT,
                        f"{why} — retained checkpoint damaged after sweep "
                        "completion; nothing regenerates it", fatal=True)
            elif which == "ckpt" and prev == []:
                ctx.add(path, "checkpoint", CORRUPT,
                        f"{why} — live set corrupt but ckpt_prev/ is sound "
                        "(resume replays from the last-good set)",
                        repair="ckpt.fallback_prev")
            elif which == "ckpt_prev" and live == []:
                ctx.add(path, "checkpoint", STALE,
                        f"{why} — last-good fallback damaged but the live "
                        "set is sound; the next checkpoint swap replaces "
                        "it")
            else:
                ctx.add(path, "checkpoint", INCONSISTENT,
                        f"{why} — no sound checkpoint set remains",
                        fatal=True)


# -- guardian incident ledger -------------------------------------------------

@checker
def check_guardian(ctx: ScanCtx, d: Path, files: set, dirs: set) -> None:
    if "guardian.json" not in files:
        return
    path = d / "guardian.json"
    data = ctx.read_bytes(path, "guardian_ledger")
    if data is None:
        return
    try:
        raw = json.loads(data)
    except ValueError as e:
        ctx.add(path, "guardian_ledger", INCONSISTENT,
                f"unparseable incident ledger: {e} — a resume would "
                "silently forget quarantines and spent rollback budget",
                fatal=True)
        return
    state = check_payload_digest(raw)
    if state == "mismatch":
        ctx.add(path, "guardian_ledger", INCONSISTENT,
                "payload digest mismatch — recorded incidents cannot be "
                "trusted (LedgerCorruptionError on load)", fatal=True)
    elif state == "absent":
        ctx.add(path, "guardian_ledger", STALE,
                "digest-less legacy ledger (loads unverified; rewritten "
                "with a digest on its next incident)")


# -- executable cache ---------------------------------------------------------

@checker
def check_xcache(ctx: ScanCtx, d: Path, files: set, dirs: set) -> None:
    """Entries self-validate (header sha256, xcache/store.py); the LRU
    manifest and warmup manifest are bookkeeping over the same directory
    — cheap to reconcile, never ground truth, so every defect here is
    repairable (worst case: one fresh compile)."""
    if "exec" not in dirs:
        return
    from sparse_coding_tpu.xcache.store import EntryCorruptError, _unpack_entry

    exec_dir = d / "exec"
    entries: Optional[dict] = None
    man = d / "manifest.json"
    bins = sorted(exec_dir.glob("*.bin"))
    if "manifest.json" in files:
        data = ctx.read_bytes(man, "xcache")
        if data is not None:
            try:
                parsed = json.loads(data)
                entries = dict(parsed.get("entries", {}))
            except (ValueError, TypeError) as e:
                ctx.add(man, "xcache", CORRUPT,
                        f"unparseable LRU manifest: {e} (bookkeeping — "
                        "rebuilt from the directory)",
                        repair="xcache.reconcile")
    elif bins:
        ctx.add(man, "xcache", STALE,
                "LRU manifest missing with entries present (store "
                "reconciles on next write)", repair="xcache.reconcile")
    on_disk = {p.name[:-len(".bin")] for p in bins}
    for p in bins:
        key = p.name[:-len(".bin")]
        raw, err = ctx.read_quiet(p)
        if raw is None:
            ctx.add(p, "xcache", CORRUPT, f"unreadable entry: {err} "
                    "(safe to drop — the caller recompiles)",
                    repair="xcache.drop_entry")
            continue
        try:
            _unpack_entry(raw)
        except EntryCorruptError as e:
            ctx.add(p, "xcache", CORRUPT,
                    f"entry failed self-validation: {e} (safe to drop — "
                    "the caller recompiles)", repair="xcache.drop_entry")
            continue
        if entries is None or key not in entries:
            if entries is not None:
                ctx.add(p, "xcache", ORPHAN,
                        "entry absent from the LRU manifest (a crash at "
                        "the xcache.store barrier)",
                        repair="xcache.reconcile")
            continue
        rec = entries[key] if isinstance(entries[key], dict) else {}
        if int(rec.get("size", -1)) != len(raw):
            ctx.add(p, "xcache", STALE,
                    "LRU manifest size disagrees with the entry file",
                    repair="xcache.reconcile")
    for key in sorted(set(entries or ()) - on_disk):
        ctx.add(exec_dir / f"{key}.bin", "xcache", STALE,
                "LRU manifest entry with no entry file",
                repair="xcache.reconcile")
    if "warmup.json" in files:
        wdata = ctx.read_bytes(d / "warmup.json", "xcache")
        if wdata is not None:
            try:
                parsed = json.loads(wdata)
                if not isinstance(parsed, dict):
                    raise ValueError("not a dict")
            except ValueError as e:
                ctx.add(d / "warmup.json", "xcache", CORRUPT,
                        f"unparseable warmup manifest: {e} (warm starts "
                        "degrade to cold compiles)")


# -- group assignment (Group-SAE, §23) ----------------------------------------

@checker
def check_groups(ctx: ScanCtx, d: Path, files: set, dirs: set) -> None:
    """``groups.json`` (kind ``group_assignment``) is the group build's
    completion marker, written LAST: its self-digest must hold, every
    file it certifies (``similarity.npy``, each pooled
    ``group-<g>/manifest.json``) must exist and match, and every shard a
    group references must be listed by the sibling store manifest — a
    marker steering tenants at shards the store does not carry would
    train the wrong pool silently. ``group-<g>/`` dirs no group names
    are orphans (a rebuild at a smaller G leaves them behind)."""
    if "groups.json" not in files:
        return
    path = d / "groups.json"
    data = ctx.read_bytes(path, "groups")
    if data is None:
        return
    try:
        payload = json.loads(data)
    except ValueError as e:
        ctx.add(path, "groups", CORRUPT,
                f"unparseable group-assignment marker: {e}", fatal=True)
        return
    if not isinstance(payload, dict) \
            or payload.get("kind") != "group_assignment":
        return  # some other subsystem's groups.json
    state = check_payload_digest(payload)
    if state == "mismatch":
        ctx.add(path, "groups", INCONSISTENT,
                "payload digest mismatch — the group assignment cannot "
                "be trusted (GroupBuildError on load; rebuild via the "
                "group step)", fatal=True)
    elif state == "absent":
        ctx.add(path, "groups", STALE,
                "digest-less group-assignment marker (loads unverified)")
    fmap = payload.get("files", {})
    if isinstance(fmap, dict):
        for name in sorted(fmap):
            p = d / name
            if not p.exists():
                ctx.add(p, "groups", MISSING,
                        "file certified by groups.json is absent",
                        fatal=True)
                continue
            raw = ctx.read_bytes(p, "groups")
            if raw is None:
                continue
            if bytes_sha256(raw) != str(fmap[name]):
                ctx.add(p, "groups", INCONSISTENT,
                        "file bytes do not match the digest groups.json "
                        "recorded at finalize", fatal=True)
    # cross-check against the sibling store manifest: every shard a
    # group pools must exist in the store the marker sits in
    listed: Optional[set] = None
    if "manifest.json" in files:
        mdata = ctx.read_quiet(d / "manifest.json")[0]
        try:
            manifest = json.loads(mdata) if mdata is not None else None
        except ValueError:
            manifest = None  # shard_store checker owns that finding
        if isinstance(manifest, dict) \
                and manifest.get("kind") == "sharded_chunk_store":
            listed = {str(s.get("name", ""))
                      for s in manifest.get("shards", [])}
    named = set()
    for g in (payload.get("groups") or []):
        if not isinstance(g, dict):
            continue
        named.add(str(g.get("name", "")))
        if listed is None:
            continue
        for shard in (g.get("shards") or []):
            if str(shard) not in listed:
                ctx.add(path, "groups", INCONSISTENT,
                        f"group {g.get('name')!r} references shard "
                        f"{shard!r} absent from the store manifest — "
                        "tenants would train the wrong pool", fatal=True)
    for name in sorted(dirs):
        if _GROUP_RE.match(name) and name not in named:
            ctx.add(d / name, "groups", ORPHAN,
                    "group dir absent from groups.json (a rebuild at a "
                    "smaller G leaves stale pools behind)",
                    repair="groups.drop_pool")


# -- catalog ------------------------------------------------------------------

@checker
def check_catalog(ctx: ScanCtx, d: Path, files: set, dirs: set) -> None:
    if "index.json" not in files:
        return
    path = d / "index.json"
    data = ctx.read_bytes(path, "catalog")
    if data is None:
        return
    try:
        idx = json.loads(data)
        fmap = idx.get("files")
    except (ValueError, AttributeError) as e:
        ctx.add(path, "catalog", CORRUPT,
                f"unparseable completion marker index.json: {e}",
                fatal=True)
        return
    if not isinstance(fmap, dict) or "version" not in idx:
        return  # some other subsystem's index.json
    for name in sorted(fmap):
        p = d / name
        if not p.exists():
            ctx.add(p, "catalog", MISSING,
                    "file certified by the catalog index is absent",
                    fatal=True)
            continue
        raw = ctx.read_bytes(p, "catalog")
        if raw is None:
            continue
        if bytes_sha256(raw) != str(fmap[name]):
            ctx.add(p, "catalog", INCONSISTENT,
                    "file bytes do not match the digest the catalog "
                    "index recorded at finalize", fatal=True)
    for p in sorted(d.glob("*.npy")):
        if p.name not in fmap:
            ctx.add(p, "catalog", ORPHAN,
                    "array file absent from the catalog index")


# -- supervisor run dir -------------------------------------------------------

def _marker_table(config: dict) -> dict[str, tuple[Path, str]]:
    """step name -> (completion artifact, verifier) — mirrors the done()
    markers pipeline/supervisor.py's builders construct, so the journal
    cross-check and the supervisor can never disagree about what "done"
    means. Verifiers: "json" (must parse), "pickle" (pickletools-scan)."""

    def anchor(p) -> Path:
        p = Path(p)
        return p if p.is_absolute() else REPO_ROOT / p

    out: dict[str, tuple[Path, str]] = {}
    try:
        harvest = config.get("harvest", {})
        if "dataset_folder" in harvest:
            dataset = anchor(harvest["dataset_folder"])
            if "n_shards" in harvest or "layers" in harvest:
                # sharded OR group (multi-tap) data plane: the store-
                # level manifest is the aggregate completion marker
                out["manifest"] = (dataset / "manifest.json", "json")
            else:
                out["harvest"] = (dataset / "meta.json", "json")
            if "group" in config:
                out["group"] = (dataset / "groups.json", "json")
        if "sweep" in config:
            sweep_out = anchor(config["sweep"]["ensemble"]["output_folder"])
            name = config["sweep"].get("experiment", "dense_l1_range")
            out["sweep"] = (sweep_out / "final"
                            / f"{name}_learned_dicts.pkl", "pickle")
        if "eval" in config:
            out["eval"] = (anchor(config["eval"]["output_folder"])
                           / "eval.json", "json")
        if "catalog" in config:
            out["catalog"] = (anchor(config["catalog"]["output_folder"])
                              / "index.json", "json")
    except (KeyError, TypeError):
        pass  # partial configs cross-check what they can
    return out


def _verify_marker(ctx: ScanCtx, path: Path, how: str) -> Optional[str]:
    """None when the artifact verifies, else the failure reason."""
    raw = ctx.read_quiet(path)[0]
    if raw is None:
        return "unreadable"
    if how == "json":
        try:
            json.loads(raw)
            return None
        except ValueError as e:
            return f"does not parse as JSON ({e})"
    if how == "pickle":
        import pickletools

        try:
            for _ in pickletools.genops(raw):
                pass
            return None
        except Exception as e:
            return f"not a complete pickle stream ({e})"
    return None


@checker
def check_leases(ctx: ScanCtx, d: Path, files: set, dirs: set) -> None:
    """Any ``leases/`` dir (supervisor run dirs, fleet dirs): a lease
    whose owner pid is dead — or an unreadable one — is exactly the
    state ``lease_state()`` already authorizes takeover over; dropping
    it is the same decision made cold."""
    if d.name != "leases":
        return
    for name in sorted(files):
        if not name.endswith(".json"):
            continue
        p = d / name
        info = read_lease(p)
        if info is None:
            ctx.add(p, "lease", STALE,
                    "unreadable lease (pre-takeover debris — no valid "
                    "claim)", repair="lease.drop")
        elif not pid_alive(info.pid):
            ctx.add(p, "lease", STALE,
                    f"lease held by dead pid {info.pid} (crashed owner — "
                    "safe takeover)", repair="lease.drop")


@checker
def check_run_dir(ctx: ScanCtx, d: Path, files: set, dirs: set) -> None:
    """A supervisor run dir: strict-scan the journal (torn-tail
    contract), then cross-check — journal says a step completed ⇒ its
    completion artifact exists AND verifies. A missing artifact is
    benign (steps are resumable by contract and re-run); an artifact
    that EXISTS but no longer verifies would be silently trusted by the
    supervisor's done() probe — that is the fatal case."""
    if "journal.jsonl" not in files:
        return
    jpath = d / "journal.jsonl"
    data = ctx.read_bytes(jpath, "journal")
    if data is None:
        return
    records, skipped, torn = _scan_jsonl(data)
    if torn:
        ctx.add(jpath, "journal", TORN,
                "unterminated final line (crash mid-append) — a "
                "truncated line can still parse as JSON and poison a "
                "fold", repair="journal.trim_tail")
    if skipped:
        ctx.add(jpath, "journal", STALE,
                f"{skipped} malformed interior line(s) skipped by the "
                "strict reader (operator edit?)")
    config = None
    if "pipeline.json" in files:
        cdata = ctx.read_bytes(d / "pipeline.json", "journal")
        if cdata is not None:
            try:
                config = json.loads(cdata)
            except ValueError as e:
                ctx.add(d / "pipeline.json", "journal", CORRUPT,
                        f"unparseable persisted pipeline config: {e} "
                        "(operators cannot rebuild this run's DAG)")
    if not isinstance(config, dict):
        return
    done = {r.get("step", "") for r in records
            if r.get("event") == "step.done"}
    for step, (marker, how) in sorted(_marker_table(config).items()):
        if step not in done:
            continue
        if not marker.exists():
            ctx.add(marker, "journal", STALE,
                    f"journal records step {step!r} done but its "
                    "completion artifact is absent (artifacts beat the "
                    "journal: the step re-runs on resume)")
            continue
        reason = _verify_marker(ctx, marker, how)
        if reason is not None:
            ctx.add(marker, "journal", INCONSISTENT,
                    f"journal records step {step!r} done and its "
                    f"completion artifact exists but {reason} — the "
                    "supervisor's done() probe would trust it and skip "
                    "the step", fatal=True)


# -- fleet tree ---------------------------------------------------------------

@checker
def check_fleet(ctx: ScanCtx, d: Path, files: set, dirs: set) -> None:
    """Fleet dir: queue replay ⇔ ``runs/<name>/`` dirs. The queue fold
    itself is torn-tail safe (pipeline/fleet_queue.py); fsck adds the
    tail finding + the existence cross-check."""
    if "fleet_queue.jsonl" not in files:
        return
    from sparse_coding_tpu.pipeline.fleet_queue import FleetQueue
    from sparse_coding_tpu.pipeline.placement import QUEUED

    qpath = d / "fleet_queue.jsonl"
    data = ctx.read_bytes(qpath, "fleet_queue")
    if data is None:
        return
    _, skipped, torn = _scan_jsonl(data)
    if torn:
        ctx.add(qpath, "fleet_queue", TORN,
                "unterminated final line (crash mid-append) — the "
                "replay fold skips it by contract",
                repair="journal.trim_tail")
    if skipped:
        ctx.add(qpath, "fleet_queue", STALE,
                f"{skipped} malformed interior line(s) skipped by the "
                "replay fold")
    state = FleetQueue(qpath).replay()
    runs_dir = d / "runs"
    for name, run in sorted(state.runs.items()):
        if run.state == QUEUED:
            continue  # never placed — no run dir expected yet
        if not (runs_dir / name).is_dir():
            ctx.add(runs_dir / name, "fleet_queue", MISSING,
                    f"queue replay says run {name!r} is {run.state} but "
                    "its run dir is absent")
    if runs_dir.is_dir():
        for sub in sorted(p for p in runs_dir.iterdir() if p.is_dir()):
            if sub.name not in state.runs:
                ctx.add(sub, "fleet_queue", ORPHAN,
                        "run dir with no fleet queue record")


# -- generic event / ledger JSONL tails ---------------------------------------

@checker
def check_event_tails(ctx: ScanCtx, d: Path, files: set, dirs: set) -> None:
    """obs event files and perf_ledger.jsonl: readers already skip a
    torn tail (obs/sink.py contract); fsck makes the tear visible and
    trims it. Journal/queue files have their own richer checkers."""
    for name in sorted(files):
        if not name.endswith(".jsonl"):
            continue
        if name in ("journal.jsonl", "fleet_queue.jsonl"):
            continue
        path = d / name
        data, err = ctx.read_quiet(path)
        if data is None:
            ctx.add(path, "events", CORRUPT, f"unreadable: {err}")
            continue
        if data and not data.endswith(b"\n"):
            ctx.add(path, "events", TORN,
                    "unterminated final line (crash mid-append; readers "
                    "skip it by contract)", repair="journal.trim_tail")
