"""CLI for the static-analysis engine.

    python -m sparse_coding_tpu.analysis [--json] [--rule ID]...
                                         [--list-rules] [paths...]

Exit status 1 when findings remain, 0 on a clean tree. ``paths``
restricts REPORTING to files under the given paths (the whole tree is
still analyzed — hatch staleness needs the full match set). The import
chain is jax-free by construction (the package ``__init__`` is lazy), so
this is safe to run while a training process owns the TPU tunnel; use
``scripts/lint.sh`` for the env-stripped belt-and-braces invocation.
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from sparse_coding_tpu.analysis import rule_ids, rule_table, run_analysis


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        prog="python -m sparse_coding_tpu.analysis",
        description="AST static analysis: reliability-convention and "
                    "JAX-hazard passes (docs/ARCHITECTURE.md §17)")
    parser.add_argument("paths", nargs="*",
                        help="restrict reported findings to these "
                             "files/directories (default: whole repo)")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="emit the machine-readable JSON report")
    parser.add_argument("--rule", action="append", default=None,
                        metavar="ID", choices=rule_ids(),
                        help="report only this rule (repeatable)")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule table and exit")
    parser.add_argument("--package", type=Path, default=None,
                        help="package dir to analyze (default: this "
                             "installed sparse_coding_tpu)")
    parser.add_argument("--repo-root", type=Path, default=None,
                        help="repo root for root-script scanning and "
                             "matrix suites (default: package parent)")
    args = parser.parse_args(argv)

    if args.list_rules:
        for rid, desc in rule_table().items():
            print(f"{rid:20s} {desc}")
        return 0

    package = args.package or Path(__file__).resolve().parent.parent
    repo_root = args.repo_root or package.parent
    result = run_analysis(package=package, repo_root=repo_root,
                          rules=args.rule)

    findings = result.findings
    if args.paths:
        prefixes = []
        for p in args.paths:
            rp = Path(p).resolve()
            try:
                prefixes.append(rp.relative_to(repo_root).as_posix())
            except ValueError:
                prefixes.append(str(p))
        findings = [f for f in findings
                    if any(f.rel == pre or f.rel.startswith(pre + "/")
                           for pre in prefixes)]

    if args.as_json:
        payload = result.to_json()
        payload["findings"] = [
            {"rule": f.rule, "file": f.rel, "line": f.line,
             "message": f.message} for f in findings]
        payload["counts"] = {}
        for f in findings:
            payload["counts"][f.rule] = payload["counts"].get(f.rule, 0) + 1
        print(json.dumps(payload, indent=2))
    else:
        for f in findings:
            print(f.render())
        n = len(findings)
        print(f"{result.meta.get('files_scanned', 0)} files scanned, "
              f"{n} finding{'s' if n != 1 else ''}", file=sys.stderr)
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
