"""The six regex lint suites, ported onto the shared AST engine.

Each pass preserves its legacy test's verdicts (tests/test_*_lint.py are
now thin wrappers asserting these passes' findings, planted-violation
self-tests included) but matches on the parsed tree instead of re-running
a per-suite regex walk:

- ``bare-write``     — tests/test_atomic_write_lint.py's convention
- ``raw-timer``      — tests/test_obs_lint.py's convention
- ``raw-profiler``   — tests/test_profiler_lint.py's convention
- ``bare-compile``   — tests/test_xcache_lint.py's convention

(the fault/crash coverage lints live in ``coverage.py``; the JAX-hazard
passes regex could never express live in ``hazards.py``/``nondet.py``.)

AST matching is strictly more precise than the old line regexes in the
directions that were documented as acceptable false-negatives: a pattern
named in a comment or docstring is not a call, and a default argument
like ``clock=time.time`` is a reference, not a read.
"""

from __future__ import annotations

import ast
from typing import Iterable

from sparse_coding_tpu.analysis.core import (
    FileCtx,
    Match,
    Pass,
    RepoCtx,
    dotted_name,
    register,
)


def _in_package(ctx: FileCtx) -> bool:
    return ctx.rel.startswith("sparse_coding_tpu/")


def _pkg_rel(ctx: FileCtx) -> str:
    """path relative to the package dir ('' for repo-root scripts)."""
    if _in_package(ctx):
        return ctx.rel.split("/", 1)[1]
    return ""


def _calls(tree: ast.AST) -> Iterable[ast.Call]:
    for node in ast.walk(tree):
        if isinstance(node, ast.Call):
            yield node


@register
class BareWritePass(Pass):
    """Shared-path artifacts must go through resilience/atomic.py's
    tmp+fsync+rename helpers — a bare ``write_text``/``write_bytes``/
    ``np.save``/``pickle.dump`` lets a crash (or a concurrent reader)
    observe a truncated file at the final name."""

    rule = "bare-write"
    description = ("bare write_text/write_bytes/np.save/pickle.dump in "
                   "package code — use resilience.atomic, or excuse a "
                   "provably process-private path")

    # whole file implementing the sanctioned primitives (its internal
    # buffer writes are the mechanism, not a violation)
    ALLOWED_FILES = ("resilience/atomic.py",)

    def run(self, ctx: FileCtx, repo: RepoCtx) -> Iterable[Match]:
        in_scope = (_in_package(ctx)
                    and _pkg_rel(ctx) not in self.ALLOWED_FILES)
        for call in _calls(ctx.tree):
            func = call.func
            if not isinstance(func, ast.Attribute):
                continue
            name = dotted_name(func)
            hit = (func.attr in ("write_text", "write_bytes")
                   or name in ("np.save", "pickle.dump"))
            if not hit:
                continue
            line = ctx.line_of(call, f".{func.attr}(")
            yield Match(self.rule, ctx.rel, line,
                        call.end_lineno or line, ctx.src(line),
                        in_scope=in_scope)


@register
class RawTimerPass(Pass):
    """Hot-path subsystems must not read raw clocks ad hoc — timing goes
    through obs (obs.monotime, obs.span/record_span, StepTimer) so every
    duration lands in the registry/event stream obs.report merges."""

    rule = "raw-timer"
    description = ("ad-hoc time.time()/time.monotonic()/"
                   "time.perf_counter() in a hot-path subsystem — route "
                   "timing through obs (docs/ARCHITECTURE.md §12)")

    LINTED_DIRS = ("data/", "train/", "serve/", "pipeline/")
    CLOCKS = ("time", "monotonic", "perf_counter")

    def run(self, ctx: FileCtx, repo: RepoCtx) -> Iterable[Match]:
        in_scope = _pkg_rel(ctx).startswith(self.LINTED_DIRS)
        for call in _calls(ctx.tree):
            if dotted_name(call.func) in [f"time.{c}" for c in self.CLOCKS]:
                line = ctx.line_of(call, "time.")
                yield Match(self.rule, ctx.rel, line,
                            call.end_lineno or line, ctx.src(line),
                            in_scope=in_scope)


@register
class RawProfilerPass(Pass):
    """Device-trace capture goes through obs.trace.capture/TraceCapture:
    an unmanaged start_trace/stop_trace pair has no exception-path
    guarantee and writes straight into its final directory, so a crash
    mid-capture leaves a half-written artifact indistinguishable from a
    real one."""

    rule = "raw-profiler"
    description = ("bare jax.profiler.start_trace/stop_trace outside "
                   "obs/trace.py — use obs.trace.capture / TraceCapture "
                   "(docs/ARCHITECTURE.md §12)")

    # the managed wrapper itself is the one sanctioned home of the raw API
    EXEMPT = ("obs/trace.py",)

    def run(self, ctx: FileCtx, repo: RepoCtx) -> Iterable[Match]:
        in_scope = _pkg_rel(ctx) not in self.EXEMPT
        for call in _calls(ctx.tree):
            func = call.func
            if not (isinstance(func, ast.Attribute)
                    and func.attr in ("start_trace", "stop_trace")):
                continue
            base = func.value
            is_profiler = (isinstance(base, ast.Name)
                           and base.id == "profiler") or (
                isinstance(base, ast.Attribute) and base.attr == "profiler")
            if not is_profiler:
                continue
            line = ctx.line_of(call, f".{func.attr}(")
            yield Match(self.rule, ctx.rel, line,
                        call.end_lineno or line, ctx.src(line),
                        in_scope=in_scope)


@register
class BareCompilePass(Pass):
    """AOT compile chains in serve/ and train/ go through
    xcache.cached_compile so every program joins the persistent
    executable cache, the warmup manifest, and the xcache fault/crash
    story — a bare .lower(...).compile() silently re-pays XLA compile on
    every restart."""

    rule = "bare-compile"
    description = ("bare jit(...).lower(...).compile() call site — route "
                   "AOT compilation through xcache.cached_compile "
                   "(docs/ARCHITECTURE.md §13)")

    LINTED_DIRS = ("serve/", "train/")

    def run(self, ctx: FileCtx, repo: RepoCtx) -> Iterable[Match]:
        in_scope = _pkg_rel(ctx).startswith(self.LINTED_DIRS)
        for call in _calls(ctx.tree):
            func = call.func
            if not (isinstance(func, ast.Attribute)
                    and func.attr == "compile"
                    and isinstance(func.value, ast.Call)
                    and isinstance(func.value.func, ast.Attribute)
                    and func.value.func.attr == "lower"):
                continue
            # report the .lower( line, as the legacy multi-line regex did
            line = ctx.line_of(call, ".lower")
            yield Match(self.rule, ctx.rel, line,
                        call.end_lineno or line, ctx.src(line),
                        in_scope=in_scope)
