"""Unified static-analysis engine (docs/ARCHITECTURE.md §17).

One parse per file, many passes per parse: each ``*.py`` under the
package (plus the repo-root scripts) is read ONCE into a
:class:`FileCtx` — source text, split lines, ``ast`` tree, and a
tokenize-accurate comment map — and every registered pass walks that
shared tree emitting :class:`Match` records. The engine then applies the
single escape-hatch protocol (``# lint: allow-<rule> <why>``, reason
mandatory) and the per-rule scope to turn matches into
:class:`Finding`s, and finally cross-checks every hatch against the
match set so a hatch whose line no longer triggers its rule fails the
build instead of rotting silently.

Matches vs findings: a pass reports every place its pattern occurs
(``in_scope`` marks whether the rule's convention actually covers that
file) so hatch staleness can be judged pattern-level — moving a file out
of a rule's scope does not strand its hatches — while findings are only
the in-scope, unexcused matches.

This module is deliberately import-light: no jax, no numpy — the CLI
(``python -m sparse_coding_tpu.analysis``) must run under a wedged TPU
tunnel without ever touching the axon plugin.
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass, field
from pathlib import Path
from typing import Callable, Iterable, Optional

HATCH_RE = re.compile(r"#\s*lint:\s*allow-([A-Za-z0-9_-]+)[ \t]*(.*)$")


@dataclass(frozen=True)
class Hatch:
    """One ``# lint: allow-<rule> <why>`` escape-hatch comment."""

    rule: str
    reason: str
    line: int


@dataclass(frozen=True)
class Match:
    """A pattern occurrence reported by a pass (pre-excusal, pre-scope).

    ``line``..``end_line`` is the excusable span: a hatch on any line of
    the span excuses the match (multi-line call chains put the hatch
    wherever it reads best, as the legacy bare-compile lint allowed).
    """

    rule: str
    rel: str
    line: int
    end_line: int
    message: str
    in_scope: bool = True


@dataclass(frozen=True)
class Finding:
    """An in-scope, unexcused match — what the build fails on."""

    rule: str
    rel: str
    line: int
    message: str

    def render(self) -> str:
        return f"{self.rel}:{self.line}: [{self.rule}] {self.message}"


class FileCtx:
    """One parsed file: text, lines, AST, comments, and hatches."""

    def __init__(self, path: Path, rel: str):
        self.path = path
        self.rel = rel
        self.text = path.read_text()
        self.lines = self.text.splitlines()
        self.parse_error: Optional[SyntaxError] = None
        try:
            self.tree: Optional[ast.AST] = ast.parse(self.text)
        except SyntaxError as err:
            self.tree = None
            self.parse_error = err
        self.comments: dict[int, str] = self._comment_map()
        self.hatches: dict[int, Hatch] = {}
        for lineno, comment in self.comments.items():
            m = HATCH_RE.search(comment)
            if m:
                self.hatches[lineno] = Hatch(rule=m.group(1),
                                             reason=m.group(2).strip(),
                                             line=lineno)

    def _comment_map(self) -> dict[int, str]:
        """line -> comment text, from real COMMENT tokens only (a hatch
        string quoted inside a docstring is documentation, not a hatch)."""
        out: dict[int, str] = {}
        try:
            for tok in tokenize.generate_tokens(
                    io.StringIO(self.text).readline):
                if tok.type == tokenize.COMMENT:
                    out[tok.start[0]] = tok.string
        except (tokenize.TokenError, IndentationError, SyntaxError):
            # unparseable tail: fall back to a naive scan so hatches on
            # the intact prefix still register
            for i, line in enumerate(self.lines, 1):
                if "#" in line:
                    out[i] = line[line.index("#"):]
        return out

    def line_of(self, node: ast.AST, needle: str) -> int:
        """First line in ``node``'s span whose source contains ``needle``
        (the legacy lints reported e.g. the ``.lower(`` line of a
        multi-line chain); falls back to ``node.lineno``."""
        start = node.lineno
        end = getattr(node, "end_lineno", start) or start
        for i in range(start, min(end, len(self.lines)) + 1):
            if needle in self.lines[i - 1]:
                return i
        return start

    def src(self, lineno: int) -> str:
        if 1 <= lineno <= len(self.lines):
            return self.lines[lineno - 1].strip()
        return ""


@dataclass
class RepoCtx:
    """Cross-file state shared by all passes in one run."""

    package: Path
    repo_root: Optional[Path] = None
    fault_matrix_text: str = ""
    crash_matrix_text: str = ""
    meta: dict = field(default_factory=dict)


@dataclass
class AnalysisResult:
    findings: list[Finding]
    matches: list[Match]
    hatches: list[tuple[str, Hatch]]  # (rel, hatch)
    meta: dict

    def for_rule(self, rule: str) -> list[Finding]:
        return [f for f in self.findings if f.rule == rule]

    def render(self) -> list[str]:
        return [f.render() for f in self.findings]

    def to_json(self) -> dict:
        counts: dict[str, int] = {}
        for f in self.findings:
            counts[f.rule] = counts.get(f.rule, 0) + 1
        return {
            "findings": [{"rule": f.rule, "file": f.rel, "line": f.line,
                          "message": f.message} for f in self.findings],
            "counts": counts,
            "files_scanned": self.meta.get("files_scanned", 0),
            "hatches": [{"file": rel, "line": h.line, "rule": h.rule,
                         "reason": h.reason} for rel, h in self.hatches],
        }


class Pass:
    """Base pass: subclasses set ``rule`` and implement :meth:`run`.

    ``rule`` doubles as the escape-hatch suffix: ``# lint: allow-<rule>
    <why>`` on any line of a match's span excuses it.
    """

    rule: str = ""
    description: str = ""

    def run(self, ctx: FileCtx, repo: RepoCtx) -> Iterable[Match]:
        raise NotImplementedError


# registry ----------------------------------------------------------------

_REGISTRY: dict[str, Callable[[], Pass]] = {}


def register(cls):
    """Class decorator: add a pass to the default registry."""
    _REGISTRY[cls.rule] = cls
    return cls


def rule_ids() -> list[str]:
    return sorted(_REGISTRY) + [PARSE_ERROR_RULE, STALE_HATCH_RULE]


PARSE_ERROR_RULE = "parse-error"
STALE_HATCH_RULE = "stale-hatch"
STALE_HATCH_DESCRIPTION = (
    "escape hatches must stay earned: a '# lint: allow-<rule> <why>' "
    "comment whose line no longer triggers <rule>, names an unknown "
    "rule, or omits the mandatory <why> reason fails the build")


def _iter_files(package: Path, repo_root: Optional[Path]):
    """(path, rel) for every scanned file, package first then repo-root
    scripts; rel is the display path (package-parent- or repo-relative)."""
    base = package.parent
    for path in sorted(package.rglob("*.py")):
        yield path, path.relative_to(base).as_posix()
    if repo_root is not None:
        for path in sorted(repo_root.glob("*.py")):
            yield path, path.relative_to(repo_root).as_posix()


def _stale_hatch_findings(ctxs: list[FileCtx],
                          matches: list[Match]) -> list[Finding]:
    known = set(_REGISTRY)
    covered: dict[tuple[str, str], list[tuple[int, int]]] = {}
    for m in matches:
        covered.setdefault((m.rel, m.rule), []).append((m.line, m.end_line))
    out = []
    for ctx in ctxs:
        if ctx.parse_error is not None:
            # no pass ran on this file: hatch staleness is unjudgeable
            # (the parse-error finding already fails the build)
            continue
        for h in ctx.hatches.values():
            if h.rule not in known:
                out.append(Finding(
                    STALE_HATCH_RULE, ctx.rel, h.line,
                    f"escape hatch names unknown rule 'allow-{h.rule}' "
                    f"(known: {', '.join(sorted(known))})"))
                continue
            if not h.reason:
                out.append(Finding(
                    STALE_HATCH_RULE, ctx.rel, h.line,
                    f"escape hatch 'allow-{h.rule}' has no reason — "
                    "'# lint: allow-<rule> <why>' requires the <why>"))
            spans = covered.get((ctx.rel, h.rule), ())
            if not any(lo <= h.line <= hi for lo, hi in spans):
                out.append(Finding(
                    STALE_HATCH_RULE, ctx.rel, h.line,
                    f"stale escape hatch: this line no longer triggers "
                    f"rule '{h.rule}' — delete the "
                    f"'# lint: allow-{h.rule}' comment"))
    return out


def run_analysis(package: Path | str,
                 repo_root: Path | str | None = None,
                 rules: Optional[Iterable[str]] = None,
                 fault_matrix_text: Optional[str] = None,
                 crash_matrix_text: Optional[str] = None) -> AnalysisResult:
    """Parse every file once and run every registered pass over it.

    All passes always execute (hatch staleness needs the full match set);
    ``rules`` only filters the REPORTED findings. Matrix texts default to
    the repo's fault/chaos suites when ``repo_root`` is given, else empty
    (scratch trees in tests pass their own).
    """
    package = Path(package)
    repo_root = Path(repo_root) if repo_root is not None else None
    if fault_matrix_text is None:
        fault_matrix_text = _read_matrix(repo_root, "test_resilience.py")
    if crash_matrix_text is None:
        crash_matrix_text = _read_matrix(repo_root, "test_pipeline_chaos.py")
    repo = RepoCtx(package=package, repo_root=repo_root,
                   fault_matrix_text=fault_matrix_text,
                   crash_matrix_text=crash_matrix_text)
    passes = [cls() for _, cls in sorted(_REGISTRY.items())]

    ctxs: list[FileCtx] = []
    matches: list[Match] = []
    findings: list[Finding] = []
    for path, rel in _iter_files(package, repo_root):
        ctx = FileCtx(path, rel)
        ctxs.append(ctx)
        if ctx.parse_error is not None:
            findings.append(Finding(
                PARSE_ERROR_RULE, rel, ctx.parse_error.lineno or 1,
                f"file does not parse: {ctx.parse_error.msg}"))
            continue
        for p in passes:
            matches.extend(p.run(ctx, repo))

    hatch_by_rel = {ctx.rel: ctx.hatches for ctx in ctxs}
    for m in matches:
        if not m.in_scope:
            continue
        file_hatches = hatch_by_rel.get(m.rel, {})
        if any(h.rule == m.rule
               for ln in range(m.line, m.end_line + 1)
               if (h := file_hatches.get(ln)) is not None):
            continue
        findings.append(Finding(m.rule, m.rel, m.line, m.message))

    findings.extend(_stale_hatch_findings(ctxs, matches))

    if rules is not None:
        # parse errors always survive the filter: a file no pass could
        # analyze makes every rule's verdict on it meaningless
        wanted = set(rules) | {PARSE_ERROR_RULE}
        findings = [f for f in findings if f.rule in wanted]
    findings.sort(key=lambda f: (f.rel, f.line, f.rule))
    repo.meta["files_scanned"] = len(ctxs)
    hatches = [(ctx.rel, h) for ctx in ctxs for h in ctx.hatches.values()]
    return AnalysisResult(findings=findings, matches=matches,
                          hatches=hatches, meta=repo.meta)


def _read_matrix(repo_root: Optional[Path], name: str) -> str:
    if repo_root is None:
        return ""
    path = repo_root / "tests" / name
    return path.read_text() if path.exists() else ""


# shared AST helpers ------------------------------------------------------

def dotted_name(node: ast.AST) -> str:
    """'jax.profiler.start_trace' for a Name/Attribute chain, '' if the
    chain bottoms out in anything else (a call, a subscript, ...)."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return ""


def last_segment(node: ast.AST) -> str:
    """Final attribute/name of a callee expression ('item' for
    ``x.y.item``), '' when the callee is not a name chain tail."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return ""
