"""JAX dispatch-discipline passes: host-sync hazards and donation safety.

These are the bug classes the legacy regex lints could never express —
both need the parsed tree plus intra-function dataflow:

``host-sync``
    ``float()`` / ``int()`` / ``bool()`` / ``.item()`` / ``np.asarray()``
    applied to a device-array-producing expression INSIDE a per-step hot
    loop forces a device→host transfer per iteration, which stalls XLA's
    async dispatch pipeline (the ROADMAP item-2 MFU plateau is partly
    this). Scope detection is conservative: a value is "device-array-
    producing" only when it taints back, through assignments in the same
    function, to a call of a jitted step (a name bound to
    ``jax.jit``/``pjit``/``shard_map``/``cached_compile`` — directly, via
    a local factory, or via a ``*step*``-named callable); a sink is only
    flagged inside a ``for``/``while`` body. Reads already batched
    through ``jax.device_get`` are host values and never flagged —
    that IS the fix.

``donation``
    invocations of donated executables (``donate_argnums``/``donate``)
    whose donated argument reaches back, via intra-function assignment
    chains, to externally-owned memory: ``np.frombuffer``/``memoryview``
    views (and view-producing methods on them), checkpoint-restore
    payloads (``*restore*``/``from_bytes`` results), or raw function
    parameters never materialized through ``jnp.array(...)``. This is
    the PR-5 use-after-release class: a cache-loaded executable retains
    input-output aliasing that a fresh CPU compile drops, so donating a
    buffer jax does not own turns the first step into heap corruption
    (docs/ARCHITECTURE.md §13, "donation rule").

Both passes fail open-eyed: what they cannot resolve they do not flag
(a finding should always be worth reading), and the standard
``# lint: allow-host-sync <why>`` / ``# lint: allow-donation <why>``
hatches excuse audited boundary syncs and provably-owned donations.
"""

from __future__ import annotations

import ast
from typing import Iterable, Optional

from sparse_coding_tpu.analysis.core import (
    FileCtx,
    Match,
    Pass,
    RepoCtx,
    dotted_name,
    last_segment,
    register,
)
from sparse_coding_tpu.analysis.legacy import _pkg_rel

JIT_WRAPPERS = ("jit", "pjit", "shard_map", "cached_compile")
SANITIZERS = ("jax.device_get", "device_get")
MATERIALIZERS = ("jnp.array", "jax.numpy.array")
TREE_MAPS = ("jax.tree.map", "jax.tree_map", "jax.tree_util.tree_map")
NP_SYNCS = ("np.asarray", "numpy.asarray", "np.array", "numpy.array")


class ModuleInfo:
    """Per-module facts shared by both passes: which names are jitted
    callables, which functions are factories returning them, and which
    ``self.<attr>`` slots classes bind them to."""

    def __init__(self, tree: ast.AST):
        self.functions: dict[str, ast.FunctionDef] = {}
        self.classes: list[ast.ClassDef] = []
        self.jitted_names: set[str] = set()
        self.factory_names: set[str] = set()
        for node in tree.body if isinstance(tree, ast.Module) else []:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.functions[node.name] = node
                if self._decorated_jit(node):
                    self.jitted_names.add(node.name)
            elif isinstance(node, ast.ClassDef):
                self.classes.append(node)
            elif isinstance(node, ast.Assign):
                if self._is_jitty_value(node.value):
                    for t in node.targets:
                        if isinstance(t, ast.Name):
                            self.jitted_names.add(t.id)
        # factories: functions returning a jit-wrapped callable, chased to
        # a small fixpoint so factory-calls-factory chains resolve
        changed = True
        while changed:
            changed = False
            for name, fn in self.functions.items():
                if name in self.factory_names:
                    continue
                for ret in ast.walk(fn):
                    if isinstance(ret, ast.Return) and ret.value is not None \
                            and self._is_jitty_value(ret.value):
                        self.factory_names.add(name)
                        changed = True
                        break

    @staticmethod
    def _decorated_jit(fn) -> bool:
        for dec in fn.decorator_list:
            if last_segment(dec) in JIT_WRAPPERS:
                return True
            if isinstance(dec, ast.Call):
                if last_segment(dec.func) in JIT_WRAPPERS:
                    return True
                if last_segment(dec.func) == "partial" and dec.args and \
                        last_segment(dec.args[0]) in JIT_WRAPPERS:
                    return True
        return False

    def _is_jitty_value(self, node: ast.AST) -> bool:
        if not isinstance(node, ast.Call):
            return False
        if last_segment(node.func) in JIT_WRAPPERS:
            return True
        callee = last_segment(node.func)
        return callee in self.factory_names or callee in self.jitted_names


def _walk_functions(tree: ast.AST):
    """Every FunctionDef in the module, each paired with its enclosing
    class (or None) — nested functions are yielded as their own scopes."""
    def visit(node, cls):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                yield child, cls
                yield from visit(child, cls)
            elif isinstance(child, ast.ClassDef):
                yield from visit(child, child)
            else:
                yield from visit(child, cls)
    yield from visit(tree, None)


# host-sync ----------------------------------------------------------------

@register
class HostSyncPass(Pass):
    rule = "host-sync"
    description = ("float()/int()/bool()/.item()/np.asarray() on a "
                   "device-array value inside a per-step hot loop — a "
                   "host sync per iteration stalls XLA pipelining; batch "
                   "reads with one jax.device_get per log window")

    LINTED_DIRS = ("data/", "train/", "serve/")

    def run(self, ctx: FileCtx, repo: RepoCtx) -> Iterable[Match]:
        in_scope = _pkg_rel(ctx).startswith(self.LINTED_DIRS)
        info = ModuleInfo(ctx.tree)
        seen: set[tuple[int, str]] = set()
        for fn, _cls in _walk_functions(ctx.tree):
            analyzer = _TaintAnalyzer(info)
            for sink_line, sink_desc in analyzer.analyze(fn):
                if (sink_line, sink_desc) in seen:
                    continue
                seen.add((sink_line, sink_desc))
                yield Match(
                    self.rule, ctx.rel, sink_line, sink_line,
                    f"{sink_desc} forces a device→host sync every "
                    "iteration of this hot loop — batch the reads with "
                    "one jax.device_get per window, or excuse a true "
                    "boundary sync with '# lint: allow-host-sync <why>'",
                    in_scope=in_scope)


class _TaintAnalyzer:
    """Intra-function taint: values returned by jitted-step calls are
    device arrays; syncing builtins applied to them inside a loop are
    sinks. Two statement passes give loop-carried assignments a chance
    to taint before sinks are judged."""

    SYNC_BUILTINS = ("float", "int", "bool")

    def __init__(self, info: ModuleInfo):
        self.info = info
        self.taint: set[str] = set()
        self.local_jitted: set[str] = set()
        self.sinks: list[tuple[int, str]] = []
        self.emit = False

    def analyze(self, fn) -> list[tuple[int, str]]:
        for final in (False, True):
            self.emit = final
            self.loop_depth = 0
            self._stmts(fn.body)
        return self.sinks

    # -- steppy-call detection --------------------------------------------

    def _is_step_call(self, call: ast.Call) -> bool:
        func = call.func
        seg = last_segment(func)
        if not seg:
            return False
        if seg in self.local_jitted or seg in self.info.jitted_names:
            return True
        if isinstance(func, ast.Name) and seg in self.info.factory_names:
            # calling the factory returns the step, it does not run it
            return False
        return "step" in seg.lower()

    def _is_jitty_local(self, value: ast.AST) -> bool:
        if self.info._is_jitty_value(value):
            return True
        # stepper = ensemble.run_steps — binding a step method
        return (isinstance(value, (ast.Attribute, ast.Name))
                and "step" in (last_segment(value) or "").lower())

    # -- statements -------------------------------------------------------

    def _stmts(self, body) -> None:
        for node in body:
            self._stmt(node)

    def _stmt(self, node: ast.AST) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            return  # own scope, analyzed separately
        if isinstance(node, ast.Assign):
            if self._is_jitty_local(node.value):
                for t in node.targets:
                    if isinstance(t, ast.Name):
                        self.local_jitted.add(t.id)
            t = self._ev(node.value)
            for target in node.targets:
                self._assign(target, t)
        elif isinstance(node, ast.AnnAssign):
            if node.value is not None:
                if self._is_jitty_local(node.value) and isinstance(
                        node.target, ast.Name):
                    self.local_jitted.add(node.target.id)
                self._assign(node.target, self._ev(node.value))
        elif isinstance(node, ast.AugAssign):
            t = self._ev(node.value) or (
                isinstance(node.target, ast.Name)
                and node.target.id in self.taint)
            self._assign(node.target, t)
        elif isinstance(node, ast.For):
            self._assign(node.target, self._ev(node.iter))
            self.loop_depth += 1
            self._stmts(node.body)
            self.loop_depth -= 1
            self._stmts(node.orelse)
        elif isinstance(node, ast.While):
            # the condition re-evaluates every iteration: a sync there
            # (`while float(loss) > tol:`) is a per-iteration sync
            self.loop_depth += 1
            self._ev(node.test)
            self._stmts(node.body)
            self.loop_depth -= 1
            self._stmts(node.orelse)
        elif isinstance(node, ast.If):
            self._ev(node.test)
            self._stmts(node.body)
            self._stmts(node.orelse)
        elif isinstance(node, (ast.With, ast.AsyncWith)):
            for item in node.items:
                t = self._ev(item.context_expr)
                if item.optional_vars is not None:
                    self._assign(item.optional_vars, t)
            self._stmts(node.body)
        elif isinstance(node, ast.Try):
            self._stmts(node.body)
            for handler in node.handlers:
                self._stmts(handler.body)
            self._stmts(node.orelse)
            self._stmts(node.finalbody)
        elif isinstance(node, (ast.Expr, ast.Return)):
            if getattr(node, "value", None) is not None:
                self._ev(node.value)
        elif isinstance(node, ast.Raise):
            if node.exc is not None:
                self._ev(node.exc)
        elif isinstance(node, ast.Assert):
            self._ev(node.test)

    def _assign(self, target: ast.AST, tainted: bool) -> None:
        # flow-sensitive: a clean (re)binding clears taint — `losses =
        # jax.device_get(...)` and a fresh `for k, v in host.items()`
        # launder their names; the two statement passes re-taint anything
        # loop-carried
        if isinstance(target, ast.Name):
            if tainted:
                self.taint.add(target.id)
            else:
                self.taint.discard(target.id)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                self._assign(elt, tainted)
        elif isinstance(target, ast.Starred):
            self._assign(target.value, tainted)

    # -- expressions ------------------------------------------------------

    def _ev(self, node: ast.AST) -> bool:
        """Taint of an expression; emits sinks as a side effect."""
        if node is None or isinstance(node, ast.Constant):
            return False
        if isinstance(node, ast.Name):
            return node.id in self.taint
        if isinstance(node, ast.Attribute):
            return self._ev(node.value)
        if isinstance(node, ast.Subscript):
            self._ev(node.slice)
            return self._ev(node.value)
        if isinstance(node, ast.Call):
            return self._call(node)
        if isinstance(node, (ast.Tuple, ast.List, ast.Set)):
            return any([self._ev(e) for e in node.elts])
        if isinstance(node, ast.Dict):
            keys = [self._ev(k) for k in node.keys if k is not None]
            vals = [self._ev(v) for v in node.values]
            return any(keys) or any(vals)
        if isinstance(node, ast.BinOp):
            left, right = self._ev(node.left), self._ev(node.right)
            return left or right
        if isinstance(node, ast.UnaryOp):
            return self._ev(node.operand)
        if isinstance(node, ast.BoolOp):
            return any([self._ev(v) for v in node.values])
        if isinstance(node, ast.Compare):
            parts = [self._ev(node.left)] + [self._ev(c)
                                             for c in node.comparators]
            return any(parts)
        if isinstance(node, ast.IfExp):
            self._ev(node.test)
            body, orelse = self._ev(node.body), self._ev(node.orelse)
            return body or orelse
        if isinstance(node, (ast.GeneratorExp, ast.ListComp, ast.SetComp)):
            return self._comp(node, [node.elt])
        if isinstance(node, ast.DictComp):
            return self._comp(node, [node.key, node.value])
        if isinstance(node, ast.Starred):
            return self._ev(node.value)
        if isinstance(node, ast.NamedExpr):
            t = self._ev(node.value)
            self._assign(node.target, t)
            return t
        if isinstance(node, ast.Lambda):
            return False  # own scope; not analyzed from here
        if isinstance(node, ast.JoinedStr):
            for v in node.values:
                if isinstance(v, ast.FormattedValue):
                    self._ev(v.value)
            return False
        if isinstance(node, (ast.Await, ast.YieldFrom)):
            return self._ev(node.value)
        if isinstance(node, ast.Yield):
            return self._ev(node.value) if node.value else False
        return False

    def _comp(self, node, result_exprs) -> bool:
        for gen in node.generators:
            self._assign(gen.target, self._ev(gen.iter))
            for cond in gen.ifs:
                self._ev(cond)
        results = [self._ev(e) for e in result_exprs]
        return any(results)

    def _call(self, call: ast.Call) -> bool:
        func = call.func
        dn = dotted_name(func)
        arg_taints = [self._ev(a) for a in call.args]
        kw_taints = [self._ev(kw.value) for kw in call.keywords]

        # sinks (judged before result taint): syncing builtins
        if isinstance(func, ast.Name) and \
                func.id in self.SYNC_BUILTINS and call.args:
            if arg_taints[0]:
                self._sink(call, f"{func.id}()")
            return False  # host scalar
        if isinstance(func, ast.Attribute) and func.attr == "item" \
                and not call.args:
            if self._ev(func.value):
                self._sink(call, ".item()")
            return False
        if dn in NP_SYNCS:
            if call.args and arg_taints[0]:
                self._sink(call, f"{dn}()")
            return False  # host array
        if dn in SANITIZERS:
            return False  # the sanctioned batched read: host values out
        if self._is_step_call(call):
            return True  # device-array-producing seed
        # pass-through: tainted inputs (or a method on a tainted object,
        # e.g. metrics.items()) produce tainted outputs
        base_taint = (isinstance(func, ast.Attribute)
                      and self._ev(func.value))
        return bool(base_taint or any(arg_taints) or any(kw_taints))

    def _sink(self, call: ast.Call, what: str) -> None:
        if self.emit and self.loop_depth > 0:
            self.sinks.append((call.lineno, what))


# donation safety ----------------------------------------------------------

@register
class DonationSafetyPass(Pass):
    rule = "donation"
    description = ("donated executable invoked with an argument that may "
                   "alias externally-owned memory (np.frombuffer views, "
                   "checkpoint-restore payloads, raw parameters) — "
                   "materialize through jnp.array(...) first "
                   "(docs/ARCHITECTURE.md §13 donation rule)")

    def run(self, ctx: FileCtx, repo: RepoCtx) -> Iterable[Match]:
        resolver = _DonationResolver(ctx.tree)
        for fn, cls in _walk_functions(ctx.tree):
            local = resolver.local_donating(fn, cls)
            if not local:
                continue
            assigns = _assignment_map(fn)
            params = _param_names(fn)
            for call in ast.walk(fn):
                if not isinstance(call, ast.Call):
                    continue
                positions = self._donating_positions(call, local)
                if positions is _NOT_DONATING:
                    continue
                args = call.args
                # positions: ints check positional slots, strings (from
                # donate_argnames) check matching keywords, None = donating
                # but unresolvable — check every argument either way
                checked: list[tuple[str, ast.AST]] = []
                if positions is None:
                    checked = [(str(i), a) for i, a in enumerate(args)]
                    checked += [(kw.arg or "**", kw.value)
                                for kw in call.keywords]
                else:
                    checked = [(str(p), args[p]) for p in positions
                               if isinstance(p, int) and p < len(args)]
                    named = {p for p in positions if isinstance(p, str)}
                    checked += [(kw.arg, kw.value) for kw in call.keywords
                                if kw.arg in named]
                for pos, arg in checked:
                    reason = _hazard(arg, assigns, params, set(),
                                     direct=True)
                    if reason is None:
                        continue
                    yield Match(
                        self.rule, ctx.rel, call.lineno,
                        call.end_lineno or call.lineno,
                        f"argument {pos} of donated executable "
                        f"'{last_segment(call.func) or '<expr>'}' "
                        f"{reason} — donation aliases the input buffer "
                        "(use-after-release once a cache-loaded "
                        "executable retains aliasing); materialize with "
                        "jnp.array(...), or excuse a provably "
                        "runtime-owned buffer with "
                        "'# lint: allow-donation <why>'")

    @staticmethod
    def _donating_positions(call: ast.Call, local: dict):
        seg = last_segment(call.func)
        if isinstance(call.func, ast.Attribute) and isinstance(
                call.func.value, ast.Name) and call.func.value.id == "self":
            key = f"self.{seg}"
        elif isinstance(call.func, ast.Name):
            key = seg
        else:
            return _NOT_DONATING
        return local.get(key, _NOT_DONATING)


_NOT_DONATING = object()


def _param_names(fn) -> set[str]:
    a = fn.args
    names = {p.arg for p in (a.posonlyargs + a.args + a.kwonlyargs)}
    if a.vararg:
        names.add(a.vararg.arg)
    if a.kwarg:
        names.add(a.kwarg.arg)
    names.discard("self")
    names.discard("cls")
    return names


def _assignment_map(fn) -> dict[str, list[ast.AST]]:
    """name -> every expression assigned to it in this function (for-loop
    targets record the iterated expression: an element of a hazardous
    iterable is hazardous)."""
    out: dict[str, list[ast.AST]] = {}

    def add(target, value):
        if isinstance(target, ast.Name):
            out.setdefault(target.id, []).append(value)
        elif isinstance(target, (ast.Tuple, ast.List)):
            for elt in target.elts:
                add(elt, value)
        elif isinstance(target, ast.Starred):
            add(target.value, value)

    for node in ast.walk(fn):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and node is not fn:
            continue
        if isinstance(node, ast.Assign):
            for t in node.targets:
                add(t, node.value)
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            add(node.target, node.value)
        elif isinstance(node, ast.For):
            add(node.target, node.iter)
        elif isinstance(node, ast.NamedExpr):
            add(node.target, node.value)
    return out


VIEW_SAFE_METHODS = ("copy", "astype", "tolist")
HAZARD_CALL_MARKS = ("frombuffer", "memoryview")
# wrappers that preserve buffer identity (zero-copy on CPU): hazard — and
# parameter provenance — flows straight through them (§13: jnp.asarray /
# device_put wrap external memory without copying; only jnp.array owns)
ZERO_COPY_WRAPPERS = ("jnp.asarray", "jax.numpy.asarray", "np.asarray",
                      "numpy.asarray", "jax.device_put", "device_put")


def _hazard(node: ast.AST, assigns, params: set[str], visiting: set[str],
            direct: bool = True) -> Optional[str]:
    """Why ``node`` may alias externally-owned memory, or None.

    ``direct`` tracks whether the value IS the traced object (parameter
    hazards do not propagate through attribute access: ``self.state`` is
    an instance slot of unknown—assumed owned—provenance, not the
    parameter itself)."""
    if isinstance(node, ast.Name):
        if node.id in visiting:
            return None
        if node.id in assigns:
            visiting = visiting | {node.id}
            for value in assigns[node.id]:
                reason = _hazard(value, assigns, params, visiting, direct)
                if reason is not None:
                    return reason
            return None
        if direct and node.id in params:
            return (f"is the raw parameter '{node.id}', never "
                    "materialized through jnp.array(...)")
        return None
    if isinstance(node, ast.Attribute):
        return _hazard(node.value, assigns, params, visiting, direct=False)
    if isinstance(node, ast.Subscript):
        return _hazard(node.value, assigns, params, visiting, direct)
    if isinstance(node, (ast.Tuple, ast.List)):
        for elt in node.elts:
            reason = _hazard(elt, assigns, params, visiting, direct)
            if reason is not None:
                return reason
        return None
    if isinstance(node, ast.IfExp):
        return (_hazard(node.body, assigns, params, visiting, direct)
                or _hazard(node.orelse, assigns, params, visiting, direct))
    if isinstance(node, ast.Starred):
        return _hazard(node.value, assigns, params, visiting, direct)
    if isinstance(node, ast.Call):
        dn = dotted_name(node.func)
        seg = last_segment(node.func)
        if dn in MATERIALIZERS:
            return None  # jnp.array copies into a runtime-owned buffer
        if dn in TREE_MAPS and node.args and \
                dotted_name(node.args[0]) in MATERIALIZERS:
            return None  # jax.tree.map(jnp.array, tree) — the §13 idiom
        if dn in ZERO_COPY_WRAPPERS and node.args:
            return _hazard(node.args[0], assigns, params, visiting, direct)
        low = seg.lower()
        if any(mark in low for mark in HAZARD_CALL_MARKS):
            return f"flows from {seg}() (a zero-copy view of host memory)"
        if "restore" in low or low == "from_bytes":
            return (f"flows from {seg}() (checkpoint-restore payloads "
                    "are numpy views into the serialized buffer)")
        if isinstance(node.func, ast.Attribute):
            base = _hazard(node.func.value, assigns, params, visiting,
                           direct=False)
            if base is not None and seg not in VIEW_SAFE_METHODS:
                return base  # .reshape()/.view() of a view is a view
            if base is not None:
                return None
        for arg in node.args:
            reason = _hazard(arg, assigns, params, visiting, direct=False)
            if reason is not None:
                return reason
        return None
    return None


class _DonationResolver:
    """Resolve which callables in a module donate, and at which argument
    positions: direct ``jax.jit(..., donate_argnums=...)`` bindings,
    ``cached_compile`` wrappers, local factory functions, and
    ``self.<attr>`` slots bound by any method of the enclosing class."""

    def __init__(self, tree: ast.AST):
        self.tree = tree
        self.functions: dict[str, ast.FunctionDef] = {
            n.name: n for n in ast.walk(tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))}
        self._factory_memo: dict[str, object] = {}

    def local_donating(self, fn, cls) -> dict:
        """name (or 'self.attr') -> donated positions (set | None=all)."""
        out: dict = {}
        local_assigns = _assignment_map(fn)
        for name, values in local_assigns.items():
            for value in values:
                pos = self.donating_positions(value, local_assigns)
                if pos is not _NOT_DONATING:
                    out[name] = pos
        if cls is not None:
            for method in cls.body:
                if not isinstance(method, (ast.FunctionDef,
                                           ast.AsyncFunctionDef)):
                    continue
                m_assigns = _assignment_map(method)
                for node in ast.walk(method):
                    if isinstance(node, ast.Assign):
                        for t in node.targets:
                            if (isinstance(t, ast.Attribute)
                                    and isinstance(t.value, ast.Name)
                                    and t.value.id == "self"):
                                pos = self.donating_positions(
                                    node.value, m_assigns)
                                if pos is not _NOT_DONATING:
                                    out[f"self.{t.attr}"] = pos
                pos = self._factory_positions(method, 0)
                if pos is not _NOT_DONATING:
                    # a method that RETURNS a donating executable: local
                    # names bound from self.<method>(...) resolve below
                    self._factory_memo[f"self.{method.name}"] = pos
        # re-resolve local names bound from self-method factories
        for name, values in local_assigns.items():
            if name in out:
                continue
            for value in values:
                if isinstance(value, ast.Call) and isinstance(
                        value.func, ast.Attribute) and isinstance(
                        value.func.value, ast.Name) \
                        and value.func.value.id == "self":
                    key = f"self.{value.func.attr}"
                    if key in self._factory_memo:
                        out[name] = self._factory_memo[key]
        return out

    def donating_positions(self, node: ast.AST, local_assigns,
                           depth: int = 0):
        """positions donated by the executable ``node`` evaluates to, or
        _NOT_DONATING. None means "unknown positions: check all"."""
        if depth > 6 or not isinstance(node, ast.Call):
            return _NOT_DONATING
        seg = last_segment(node.func)
        if seg in ("jit", "pjit"):
            return self._positions_from_jit(node, local_assigns)
        if seg == "cached_compile" and node.args:
            return self.donating_positions(node.args[0], local_assigns,
                                           depth + 1)
        if seg in self.functions and (
                isinstance(node.func, ast.Name)
                or (isinstance(node.func, ast.Attribute)
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == "self")):
            return self._factory_positions(self.functions[seg], depth + 1)
        return _NOT_DONATING

    def _positions_from_jit(self, call: ast.Call, local_assigns):
        for kw in call.keywords:
            if kw.arg not in ("donate_argnums", "donate_argnames",
                              "donate"):
                continue
            value = kw.value
            # a bare Name resolves one hop through local assignments
            if isinstance(value, ast.Name) and local_assigns and \
                    value.id in local_assigns:
                exprs = local_assigns[value.id]
                value = ast.Tuple(elts=list(exprs), ctx=ast.Load())
            ints = [n.value for n in ast.walk(value)
                    if isinstance(n, ast.Constant)
                    and isinstance(n.value, int)
                    and not isinstance(n.value, bool)]
            # donate_argnames: string names — donated args are matched by
            # keyword at the call site (positional passing of a named
            # donation is not mapped: that needs the wrapped signature)
            names = [n.value for n in ast.walk(value)
                     if isinstance(n, ast.Constant)
                     and isinstance(n.value, str)]
            if ints or names:
                return set(ints) | set(names)
            if isinstance(kw.value, ast.Tuple) and not kw.value.elts:
                return _NOT_DONATING  # literal (): explicitly no donation
            if isinstance(kw.value, ast.Constant) and \
                    kw.value.value in (False, None):
                return _NOT_DONATING
            return None  # donating, positions unknown: check all args
        return _NOT_DONATING

    def _factory_positions(self, fn, depth: int):
        if fn.name in self._factory_memo:
            return self._factory_memo[fn.name]
        self._factory_memo[fn.name] = _NOT_DONATING  # cycle guard
        assigns = _assignment_map(fn)
        result = _NOT_DONATING
        for node in ast.walk(fn):
            if isinstance(node, ast.Return) and node.value is not None:
                value = node.value
                if isinstance(value, ast.Name) and value.id in assigns:
                    candidates = assigns[value.id]
                else:
                    candidates = [value]
                for cand in candidates:
                    pos = self.donating_positions(cand, assigns, depth)
                    if pos is not _NOT_DONATING:
                        result = pos
                        break
                if result is not _NOT_DONATING:
                    break
        self._factory_memo[fn.name] = result
        return result
