"""Bare-sharding pass: placement decisions belong to the partition layer.

The partition rule layer (``parallel/partition.py``, docs/ARCHITECTURE.md
§19) is the single home of "which leaf lives where" on the ("model",
"data") mesh: named rule sets resolve pytrees to PartitionSpecs, named
spec constants (``partition.MEMBER``/``BATCH``/...) are the vocabulary
for shard_map signatures, and every mesh device_put funnels through the
``partition.place`` fault site. A raw ``NamedSharding(...)`` or
``PartitionSpec(...)`` construction in train/serve/data/pipeline code
(or the ensemble engine) is how two call sites drift about one leaf's
placement — invisible until a resharding collective shows up in a
profile — so this pass makes the convention mechanical: construct specs
only inside ``parallel/``; everywhere else, reference the partition
layer. Escape hatch: ``# lint: allow-bare-sharding <why>`` for the rare
placement genuinely outside the layer's vocabulary.
"""

from __future__ import annotations

import ast
from typing import Iterable

from sparse_coding_tpu.analysis.core import (
    FileCtx,
    Match,
    Pass,
    RepoCtx,
    dotted_name,
    register,
)
from sparse_coding_tpu.analysis.legacy import _pkg_rel

SHARDING_CTORS = ("NamedSharding", "PartitionSpec", "PositionalSharding")
SHARDING_MODULES = ("jax.sharding", "jax.experimental.pjit")


def _ctor_aliases(tree: ast.AST) -> set[str]:
    """Local names bound to the sharding constructors, import aliases
    included (``from jax.sharding import PartitionSpec as P`` binds P)."""
    names: set[str] = set(SHARDING_CTORS)
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom) and node.module and \
                node.module.startswith(SHARDING_MODULES):
            for alias in node.names:
                if alias.name in SHARDING_CTORS:
                    names.add(alias.asname or alias.name)
    return names


@register
class BareShardingPass(Pass):
    rule = "bare-sharding"
    description = ("raw NamedSharding/PartitionSpec construction in "
                   "train/serve/data/pipeline code or the ensemble engine "
                   "— placement goes through the partition rule layer "
                   "(parallel/partition.py, docs/ARCHITECTURE.md §19): "
                   "named rule sets + spec constants, one place to drift")

    LINTED_DIRS = ("train/", "serve/", "data/", "pipeline/")
    LINTED_FILES = ("ensemble.py",)

    def run(self, ctx: FileCtx, repo: RepoCtx) -> Iterable[Match]:
        rel = _pkg_rel(ctx)
        in_scope = (rel.startswith(self.LINTED_DIRS)
                    or rel in self.LINTED_FILES)
        ctors = _ctor_aliases(ctx.tree)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, ast.Call):
                continue
            dn = dotted_name(node.func)
            if not dn:
                continue
            tail = dn.rsplit(".", 1)[-1]
            bare = dn in ctors
            dotted = "." in dn and tail in SHARDING_CTORS
            if not (bare or dotted):
                continue
            yield Match(
                self.rule, ctx.rel, node.lineno,
                node.end_lineno or node.lineno,
                f"raw {tail}(...) constructed outside parallel/ — resolve "
                "placement through the partition rule layer "
                "(parallel/partition.py: match_partition_rules / "
                "place_tree / the named spec constants), or excuse a "
                "placement outside its vocabulary with "
                "'# lint: allow-bare-sharding <why>'",
                in_scope=in_scope)
