"""Unified AST static-analysis engine (docs/ARCHITECTURE.md §17).

One parse per file, a registry of passes per parse. Public surface:

- :func:`run_analysis` — run every pass, return an
  :class:`AnalysisResult` (``findings`` / ``matches`` / ``hatches`` /
  ``meta``)
- :class:`Finding` / :class:`Match` / :class:`Hatch` — the record types
- :func:`rule_ids`, :data:`ALL_RULES` — the registered rule table
- CLI: ``python -m sparse_coding_tpu.analysis [--json] [--rule ID]
  [paths...]`` (jax-free import; safe under a wedged TPU tunnel —
  ``scripts/lint.sh`` is the one-command wrapper)

Importing the pass modules registers them; keep that import list in sync
with new pass modules.
"""

from sparse_coding_tpu.analysis.core import (
    AnalysisResult,
    FileCtx,
    Finding,
    Hatch,
    Match,
    Pass,
    RepoCtx,
    register,
    rule_ids,
    run_analysis,
)

# importing registers the passes
from sparse_coding_tpu.analysis import beats as _beats  # noqa: F401
from sparse_coding_tpu.analysis import coverage as _coverage  # noqa: F401
from sparse_coding_tpu.analysis import hazards as _hazards  # noqa: F401
from sparse_coding_tpu.analysis import legacy as _legacy  # noqa: F401
from sparse_coding_tpu.analysis import nondet as _nondet  # noqa: F401
from sparse_coding_tpu.analysis import sharding as _sharding  # noqa: F401
from sparse_coding_tpu.analysis.core import _REGISTRY, STALE_HATCH_RULE


def rule_table() -> dict[str, str]:
    """rule id -> one-line description (the §17 rule table)."""
    from sparse_coding_tpu.analysis.core import (
        PARSE_ERROR_RULE,
        STALE_HATCH_DESCRIPTION,
    )
    table = {rid: cls.description for rid, cls in sorted(_REGISTRY.items())}
    table[PARSE_ERROR_RULE] = (
        "the file does not parse — no pass can analyze it, so every "
        "rule's verdict on it would be vacuous (never filtered out)")
    table[STALE_HATCH_RULE] = STALE_HATCH_DESCRIPTION
    return table


ALL_RULES = tuple(rule_ids())

__all__ = [
    "ALL_RULES",
    "AnalysisResult",
    "FileCtx",
    "Finding",
    "Hatch",
    "Match",
    "Pass",
    "RepoCtx",
    "register",
    "rule_ids",
    "rule_table",
    "run_analysis",
]
