"""In-trace nondeterminism pass: host entropy baked into traced code.

A ``time.*``, ``random.*``, or ``np.random.*`` call lexically inside a
function that jax TRACES (decorated/wrapped with ``jit``/``pjit``/
``shard_map``, or passed to ``lax.scan``) does not re-execute per step —
it executes ONCE at trace time, baking that host value into the compiled
executable as a constant. With the persistent executable cache (§13)
the accident becomes permanent: the stale constant survives process
restarts. ``jax.random`` (functional, key-threaded) is the sanctioned
in-trace randomness and is never flagged.

Escape hatch: ``# lint: allow-in-trace-nondet <why>`` for the rare
deliberate trace-time constant (e.g. a build stamp).
"""

from __future__ import annotations

import ast
from typing import Iterable

from sparse_coding_tpu.analysis.core import (
    FileCtx,
    Match,
    Pass,
    RepoCtx,
    dotted_name,
    last_segment,
    register,
)
from sparse_coding_tpu.analysis.hazards import ModuleInfo

NONDET_PREFIXES = ("time.", "random.", "np.random.", "numpy.random.")
SCAN_CALLEES = ("jax.lax.scan", "lax.scan")
WRAP_CALLEES = ("jit", "pjit", "shard_map")


def _traced_functions(tree: ast.AST) -> list[ast.AST]:
    """FunctionDef/Lambda nodes jax will trace: jit-decorated defs, and
    defs/lambdas passed (by name, locally resolvable) to a jit wrapper
    or to lax.scan."""
    by_name: dict[str, list[ast.AST]] = {}
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            by_name.setdefault(node.name, []).append(node)

    traced: list[ast.AST] = []
    for node in ast.walk(tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)) \
                and ModuleInfo._decorated_jit(node):
            traced.append(node)
        if not isinstance(node, ast.Call):
            continue
        seg = last_segment(node.func)
        candidates: list[ast.AST] = []
        if seg in WRAP_CALLEES and node.args:
            candidates.append(node.args[0])
        if dotted_name(node.func) in SCAN_CALLEES and node.args:
            candidates.append(node.args[0])
        for cand in candidates:
            if isinstance(cand, ast.Lambda):
                traced.append(cand)
            elif isinstance(cand, ast.Name):
                traced.extend(by_name.get(cand.id, ()))
    return traced


@register
class InTraceNondetPass(Pass):
    rule = "in-trace-nondet"
    description = ("time.*/random.*/np.random.* call inside a "
                   "jit/pjit/shard_map/lax.scan-traced function — the "
                   "host value is baked into the cached executable at "
                   "trace time (use jax.random with a threaded key)")

    def run(self, ctx: FileCtx, repo: RepoCtx) -> Iterable[Match]:
        seen: set[int] = set()
        for fn in _traced_functions(ctx.tree):
            for call in ast.walk(fn):
                if not isinstance(call, ast.Call):
                    continue
                dn = dotted_name(call.func)
                if not dn or not dn.startswith(NONDET_PREFIXES):
                    continue
                if call.lineno in seen:
                    continue
                seen.add(call.lineno)
                owner = getattr(fn, "name", "<lambda>")
                yield Match(
                    self.rule, ctx.rel, call.lineno,
                    call.end_lineno or call.lineno,
                    f"{dn}() inside traced function '{owner}' executes "
                    "once at trace time and bakes a host value into the "
                    "cached executable — thread a jax.random key (or "
                    "pass the value as an argument); excuse a deliberate "
                    "trace-time constant with "
                    "'# lint: allow-in-trace-nondet <why>'")
