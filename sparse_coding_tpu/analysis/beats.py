"""Beat-coverage pass: polling loops in pipeline/ must heartbeat.

The crash-only supervision story (docs/ARCHITECTURE.md §11/§18) turns on
ONE signal: the lease heartbeat. A supervisor/scheduler process that
loops-and-sleeps while babysitting children — the shape of every
long-running work loop in ``pipeline/`` — is indistinguishable from a
wedged one unless the loop itself calls ``resilience.lease.beat()`` (or
an owned ``Lease``'s ``.beat()``) at a progress point. Heartbeats are
deliberately emitted from the work loop on the main thread, never a side
thread (resilience/lease.py): a side-thread beat would keep beating
through exactly the hang the watchdog exists to catch — so a missing
in-loop beat cannot be papered over elsewhere, and rots silently until
the first real hang. This pass makes the convention mechanical.

Detection is deliberately narrow so every finding is worth reading: a
``for``/``while`` loop in ``pipeline/`` whose body (nested included)
calls ``sleep`` — the signature of a polling loop that runs for a long
time — must lexically contain a ``beat`` call. Loops that never sleep
finish fast and are not the watchdog's concern. Escape hatch:
``# lint: allow-beat-coverage <why>`` anywhere in the loop's span.
"""

from __future__ import annotations

import ast
from typing import Iterable

from sparse_coding_tpu.analysis.core import (
    FileCtx,
    Match,
    Pass,
    RepoCtx,
    last_segment,
    register,
)
from sparse_coding_tpu.analysis.legacy import _pkg_rel


def _calls_in(node: ast.AST) -> Iterable[ast.Call]:
    for child in ast.walk(node):
        if isinstance(child, ast.Call):
            yield child


@register
class BeatCoveragePass(Pass):
    rule = "beat-coverage"
    description = ("polling loop (sleeps between iterations) in pipeline/ "
                   "with no lease heartbeat — long-running work loops must "
                   "call resilience.lease.beat() at a progress point so "
                   "the watchdog can tell working from wedged "
                   "(docs/ARCHITECTURE.md §11/§18)")

    LINTED_DIRS = ("pipeline/",)

    def run(self, ctx: FileCtx, repo: RepoCtx) -> Iterable[Match]:
        in_scope = _pkg_rel(ctx).startswith(self.LINTED_DIRS)
        for node in ast.walk(ctx.tree):
            if not isinstance(node, (ast.For, ast.AsyncFor, ast.While)):
                continue
            body = list(node.body) + list(node.orelse)
            sleeps = any(last_segment(c.func) == "sleep"
                         for stmt in body for c in _calls_in(stmt))
            if not sleeps:
                continue
            beats = any(last_segment(c.func) == "beat"
                        for stmt in body for c in _calls_in(stmt))
            if beats:
                continue
            line = ctx.line_of(node, "while " if isinstance(
                node, ast.While) else "for ")
            yield Match(
                self.rule, ctx.rel, line,
                node.end_lineno or line,
                "polling loop sleeps but never heartbeats — call "
                "resilience.lease.beat() (or the owned Lease's .beat()) "
                "at a progress point, or excuse a provably short-lived "
                "loop with '# lint: allow-beat-coverage <why>'",
                in_scope=in_scope)
