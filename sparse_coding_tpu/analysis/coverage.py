"""Fault-site and crash-site coverage passes (legacy
tests/test_fault_site_lint.py and tests/test_crash_site_lint.py ported
onto the shared engine).

Every ``register_fault_site("<site>", ...)`` needs a deterministic entry
in the fault matrix (tests/test_resilience.py), every
``register_crash_site("<site>", ...)`` — and every seed entry in
``resilience/crash.py``'s canonical ``CRASH_SITES`` table — needs a
SIGKILL case in the chaos matrix (tests/test_pipeline_chaos.py). A
failure path without its matrix case ships untested, which is exactly
the rot the injection harness exists to prevent
(docs/ARCHITECTURE.md §10/§11).

A matrix "covers" a site when it names it as a string literal (the
``inject(site="...")`` form, a compact ``site:nth=...`` plan string, or
a docstring row) — same containment check the legacy lints used, driven
from the engine's single tree walk. The collected registrations are
published in ``repo.meta['fault_sites']``/``['crash_sites']`` for the
sanity tests that guard against a vacuously-green scan.
"""

from __future__ import annotations

import ast
from typing import Iterable

from sparse_coding_tpu.analysis.core import (
    FileCtx,
    Match,
    Pass,
    RepoCtx,
    last_segment,
    register,
)
from sparse_coding_tpu.analysis.legacy import _in_package


def _literal_registrations(tree: ast.AST, register_name: str):
    """(site, lineno) for every ``<register_name>("literal", ...)`` call.
    A computed name cannot be linted and is left to review."""
    for node in ast.walk(tree):
        if (isinstance(node, ast.Call)
                and last_segment(node.func) == register_name
                and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            yield node.args[0].value, node.lineno


def _covered(site: str, matrix_text: str) -> bool:
    return (f'"{site}"' in matrix_text or f"'{site}'" in matrix_text
            or f"{site}:" in matrix_text)


class _SiteCoveragePass(Pass):
    register_name = ""
    matrix_attr = ""        # RepoCtx attribute holding the matrix text
    matrix_file = ""        # display name for messages
    meta_key = ""
    kind = ""

    def run(self, ctx: FileCtx, repo: RepoCtx) -> Iterable[Match]:
        in_scope = _in_package(ctx)
        matrix = getattr(repo, self.matrix_attr)
        sites = repo.meta.setdefault(self.meta_key, [])
        for site, lineno in self._registrations(ctx):
            excused = lineno in ctx.hatches and \
                ctx.hatches[lineno].rule == self.rule
            sites.append((site, f"{ctx.rel}:{lineno}", excused))
            if _covered(site, matrix):
                continue
            yield Match(
                self.rule, ctx.rel, lineno, lineno,
                f"{self.kind} site {site!r} has no entry in "
                f"tests/{self.matrix_file}", in_scope=in_scope)

    def _registrations(self, ctx: FileCtx):
        yield from _literal_registrations(ctx.tree, self.register_name)


@register
class UnmatrixedFaultPass(_SiteCoveragePass):
    rule = "unmatrixed-fault"
    description = ("fault site registered without a deterministic "
                   "fault-matrix entry in tests/test_resilience.py "
                   "(docs/ARCHITECTURE.md §10)")
    register_name = "register_fault_site"
    matrix_attr = "fault_matrix_text"
    matrix_file = "test_resilience.py"
    meta_key = "fault_sites"
    kind = "fault"


@register
class UnmatrixedCrashPass(_SiteCoveragePass):
    rule = "unmatrixed-crash"
    description = ("crash site registered without a SIGKILL chaos-matrix "
                   "case in tests/test_pipeline_chaos.py "
                   "(docs/ARCHITECTURE.md §11)")
    register_name = "register_crash_site"
    matrix_attr = "crash_matrix_text"
    matrix_file = "test_pipeline_chaos.py"
    meta_key = "crash_sites"
    kind = "crash"

    def _registrations(self, ctx: FileCtx):
        yield from _literal_registrations(ctx.tree, self.register_name)
        # the canonical seed table in resilience/crash.py: a child's plan
        # can parse before host modules import, so its quoted keys are
        # registrations of crash.py itself
        if ctx.rel.endswith("resilience/crash.py"):
            found = False
            for node in ast.walk(ctx.tree):
                target = None
                if isinstance(node, ast.Assign) and len(node.targets) == 1:
                    target = node.targets[0]
                elif isinstance(node, ast.AnnAssign):
                    target = node.target
                if not (isinstance(target, ast.Name)
                        and target.id == "CRASH_SITES"
                        and isinstance(getattr(node, "value", None),
                                       ast.Dict)):
                    continue
                found = True
                for key in node.value.keys:
                    if isinstance(key, ast.Constant) and isinstance(
                            key.value, str):
                        yield key.value, key.lineno
            if not found:
                # the seed table is load-bearing (docs/ARCHITECTURE.md
                # §11); its disappearance is itself a finding
                yield "(CRASH_SITES table missing)", 1
