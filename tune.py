"""Autotune harness for first TPU contact (developer tool).

The moment the TPU tunnel is healthy, `python tune.py` scans the
throughput-relevant knobs of the flagship ensemble train step at the
canonical bench scale (bench.py / BASELINE.md) and records the winner:

  stage 1 — step implementation (XLA autodiff vs fused Pallas kernel);
    for autodiff the matmul precision (default vs bfloat16); for the fused
    kernel the activation-stream dtype (f32 vs bf16, halving the x HBM
    read), the in-kernel MXU compute dtype (f32 vs bf16 — Pallas dots
    ignore jax.default_matmul_precision), and every VMEM-fitting batch
    tile;
  stage 2 — scan chunk (steps fused into one device program) for the
    stage-1 winner.

One JSON line per configuration goes to stdout as it finishes (stderr
carries diagnostics), and the best configuration is written to TUNE.json —
which bench.py picks up automatically, so the driver's end-of-round bench
runs the tuned configuration without further plumbing.

`--quick` shrinks shapes so the grid smoke-runs on CPU in ~a minute (used
by the test suite) and defaults its output to TUNE.quick.json so a smoke
run can never clobber a real TPU tuning record.
"""

from __future__ import annotations

import argparse
import itertools
import json
import sys
from pathlib import Path

import jax

from bench import _time_ensemble, chip_peak_flops, flops_per_activation

TUNE_PATH = Path(__file__).parent / "TUNE.json"
QUICK_TUNE_PATH = Path(__file__).parent / "TUNE.quick.json"

# 100/200 chase the tunnel's ~54ms/dispatch overhead further down (~4% left
# at 200). Cost is bounded: _time_ensemble floors at 3 windows, so the big
# chunks run 3×scan_chunk timed steps (~5s at bench step time) and stage a
# [scan, B, d] f32 batch stack (~800 MB at 200 on a 16 GB chip) — deliberate.
SCAN_CHUNKS = (5, 10, 25, 50, 100, 200)


def stage1_grid(on_tpu: bool, quick: bool) -> list[dict]:
    """Stage 1: step IMPLEMENTATION scan — autodiff (default / bf16 matmul
    precision) vs all four tied fused kernel paths (untiled two_stage /
    train_step AND the feature-axis-tiled pair — at the canonical ratio-4
    scale the tiled kernels are the measured A/B for the recompute trade;
    at ratio 16+ they are the only fused option), auto tiles, f32
    everywhere. Tile/dtype refinement happens in stage 1b for the winner
    only, keeping the grid tractable."""
    configs: list[dict] = [
        {"use_fused": False},
        {"use_fused": False, "matmul_precision": "bfloat16"},
    ]
    if not on_tpu:
        return configs
    configs.append({"use_fused": True, "fused_path": "two_stage"})
    configs.append({"use_fused": True, "fused_path": "train_step"})
    configs.append({"use_fused": True, "fused_path": "two_stage_tiled"})
    configs.append({"use_fused": True, "fused_path": "train_step_tiled"})
    return configs


TILED_PATHS = ("two_stage_tiled", "train_step_tiled")


def tile_grid(best: dict) -> list[dict]:
    """Stage 1b (fused winners only): explicit tiles for the winning
    kernel path (auto pick = the stage-1 winner itself). Tiled winners
    scan the (batch_tile × feat_tile) grid — the two interact through
    both kernels' VMEM working sets, so combinations are measured."""
    if not best.get("use_fused"):
        return []
    path = best.get("fused_path")
    if path in TILED_PATHS:
        return [{"use_fused": True, "fused_path": path,
                 "batch_tile": bt, "feat_tile": ft}
                for bt in (512, 256, 128)
                for ft in (4096, 2048, 1024)]
    return [{"use_fused": True, "fused_path": path,
             "batch_tile": t} for t in (2048, 1024, 512, 256, 128, 64)]


def dtype_grid(best: dict) -> list[dict]:
    """Stage 1c (fused winners only): MXU compute dtype × HBM stream dtype
    ON TOP of the tile winner — tile and dtype interact through VMEM
    admission, so the combination is measured, not inferred.
    matmul_precision doesn't reach Pallas dots; fused_compute_dtype is the
    in-kernel analogue."""
    if not best.get("use_fused"):
        return []
    # the tile winner's FULL tile pair carries into the dtype stage —
    # dropping feat_tile here would re-resolve a different tiled program
    # than the one whose rate was measured
    base = {"use_fused": True, "fused_path": best.get("fused_path"),
            "batch_tile": best.get("batch_tile"),
            "feat_tile": best.get("feat_tile")}
    configs = []
    for compute, batch_dtype in itertools.product(
            (None, "bfloat16"), (None, "bfloat16")):
        if compute is None and batch_dtype is None:
            continue  # == the tile winner itself
        configs.append({**base, "fused_compute_dtype": compute,
                        "batch_dtype": batch_dtype})
    if base.get("fused_path") in ("train_step", "train_step_tiled"):
        # opt-in bf16 moment storage (halves the whole-step kernel's
        # optimizer-state HBM traffic; documented optax-parity deviation) —
        # measured with BOTH batch streams so the moments effect is
        # isolated against each dtype-grid comparator
        for batch_dtype in (None, "bfloat16"):
            configs.append({**base, "fused_compute_dtype": "bfloat16",
                            "batch_dtype": batch_dtype,
                            "fused_moments_dtype": "bfloat16"})
    return configs


def run_config(cfg: dict, quick: bool) -> float:
    kwargs = {k: v for k, v in cfg.items() if v is not None}
    if quick:
        # an explicit n_dict survives (the ratio stage sweeps it); the
        # default quick shape is ratio 2 at d=64
        kwargs.setdefault("n_dict", 128)
        kwargs.update(d_act=64, n_members=4, batch=256, bench_steps=10)
        kwargs.setdefault("scan_chunk", 5)
    return _time_ensemble(**kwargs)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("--quick", action="store_true",
                        help="tiny shapes (CPU smoke of the grid logic); "
                             "writes TUNE.quick.json unless --out is given")
    parser.add_argument("--out", default=None)
    args = parser.parse_args()
    out_path = Path(args.out) if args.out else (
        QUICK_TUNE_PATH if args.quick else TUNE_PATH)

    backend = jax.default_backend()
    on_tpu = backend == "tpu"
    if not on_tpu and not args.quick:
        print(f"tune: backend is {backend!r}, not tpu — real tuning needs "
              "the TPU; pass --quick for a CPU smoke run", file=sys.stderr)
        sys.exit(1)

    n_chips = len(jax.devices())
    fpa = (flops_per_activation(n_members=4, n_dict=128, d_act=64)
           if args.quick else flops_per_activation())
    peak = chip_peak_flops()

    def measure(cfg: dict) -> dict | None:
        try:
            rate = run_config(cfg, args.quick)
        except Exception as e:
            print(f"tune: config {cfg} failed: {e!r}", file=sys.stderr)
            return None
        rec = {**cfg, "acts_per_sec": round(rate, 1),
               "mfu": (round(rate * fpa / peak / n_chips, 4)
                       if peak else None),
               # which kernel program actually ran (ensemble.KERNEL_PATHS
               # label or "autodiff") — the ratio stage's key output
               "resolved_path": getattr(rate, "fused_path", None)
               or "autodiff"}
        print(json.dumps(rec), flush=True)
        return rec

    results = [r for cfg in stage1_grid(on_tpu, args.quick)
               if (r := measure(cfg)) is not None]
    if not results:
        print("tune: every stage-1 configuration failed", file=sys.stderr)
        sys.exit(1)
    best = max(results, key=lambda r: r["acts_per_sec"])

    # stage 1b/1c: tile then dtype refinement for the winning implementation
    # (dtype configs inherit the tile winner, so combos are measured)
    def strip(rec: dict) -> dict:
        return {k: v for k, v in rec.items()
                if k not in ("acts_per_sec", "mfu", "resolved_path")}

    for grid_fn in (tile_grid, dtype_grid):
        for cfg in grid_fn(strip(best)):
            rec = measure(cfg)
            if rec is not None:
                results.append(rec)
                if rec["acts_per_sec"] > best["acts_per_sec"]:
                    best = rec

    # stage 2: scan-chunk sweep for the winner (roughly independent of the
    # stage-1 knobs, so sweeping it only here keeps the grid tractable)
    base = strip(best)
    scan_chunks = (5,) if args.quick else SCAN_CHUNKS
    for scan_chunk in scan_chunks:
        rec = measure({**base, "scan_chunk": scan_chunk})
        if rec is not None:
            results.append(rec)
            if rec["acts_per_sec"] > best["acts_per_sec"]:
                best = rec

    # stage 3: canonical-ratio scan (ISSUE 11) — auto-mode admission and
    # throughput at the paper's headline dict ratios (reference
    # standard_metrics.py:745 / big_sweep_experiments.py:543), recording
    # which kernel path each ratio RESOLVED to: before the feature-tiled
    # kernels, ratios ≥16 silently ran autodiff and no artifact showed it
    d_ratio = 64 if args.quick else 512
    ratio_results = []
    for ratio in (2, 4) if args.quick else (4, 16, 32):
        rec = measure({"use_fused": "auto", "n_dict": d_ratio * ratio})
        if rec is not None:
            # NOT folded into `results`/`best`: a different n_dict is a
            # different workload — its rate must never displace the
            # canonical-shape winner bench.py loads
            ratio_results.append({
                "ratio": ratio, "n_dict": d_ratio * ratio,
                "resolved_path": rec["resolved_path"],
                "acts_per_sec": rec["acts_per_sec"], "mfu": rec["mfu"]})

    out = {"backend": backend, "quick": args.quick, "best": best,
           "ratio_results": ratio_results,
           "results": sorted(results, key=lambda r: -r["acts_per_sec"])}
    out_path.write_text(json.dumps(out, indent=2))
    print(f"tune: best {best} -> {out_path}", file=sys.stderr)


if __name__ == "__main__":
    main()
