"""Extended benchmark suite (developer tool; the driver runs bench.py).

Measures every throughput-relevant path at the reference's canonical scales
(BASELINE.md) and prints one JSON object per line, so next-round tuning on
real hardware starts from a complete profile:

    python bench_suite.py [--quick]

Suites: ensemble train (autodiff + fused + bf16-precision variants), the
canonical-dict-ratio sweep (ensemble_ratio: resolved kernel path +
fused-vs-autodiff A/B at ratios 4–32 — ISSUE 11), big-SAE
train (single giant dict), activation harvesting (tokens/s through the LM
with taps), sequence-parallel long-context forward (over whatever mesh the
host offers), chunk-store IO, the guardian divergence soak (sentinel
step overhead + frozen-member/zero-rollback drill semantics), and the
device-time perf-probe overhead A/B (ISSUE 12; probe ON at default
cadence must sit within noise of OFF), and the two-tenant fleet soak
(ISSUE 14: whole-fleet throughput + tenant B's time-to-first-step
through the real scheduler, workers cpu-pinned — safe under a wedged or
busy tunnel), the feature-catalog scenario (ISSUE 16: index build
wall + top-k neighbor query latency through the gateway), and the
Group-SAE cost curve (ISSUE 19: G grouped tenants vs L per-layer
baseline tenants at a fixed per-SAE budget — wall speedup + both arms'
aggregate FVU, workers cpu-pinned). Every
scenario row also lands in the durable perf_ledger.jsonl, asserted at
exit — then GATED on (ROADMAP 3(b)): each suite row is diffed against
the last prior ledger row with the same (suite, variant, unit,
backend), and a threshold-flagged regression exits nonzero
(SPARSE_CODING_BENCH_GATE=0 disables,
SPARSE_CODING_BENCH_GATE_THRESHOLD overrides the bar).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from pathlib import Path

import jax
import jax.numpy as jnp
import numpy as np


def _timed(fn, n_iters: int, payload: float, warmup: int = 2) -> float:
    """items/sec for fn() processing `payload` items per call."""
    for _ in range(warmup):
        out = fn()
    jax.block_until_ready(out)
    t0 = time.perf_counter()
    for _ in range(n_iters):
        out = fn()
    jax.block_until_ready(out)
    return n_iters * payload / (time.perf_counter() - t0)


# emitted-vs-landed accounting for the perf ledger (ISSUE 12): every
# scenario row is also appended to perf_ledger.jsonl, and main() asserts
# at exit that the rows actually landed — a silently-broken ledger would
# otherwise rot the round-over-round regression record
_LEDGER = {"emitted": 0, "appended": 0}


def _emit(suite: str, value: float, unit: str, **extra) -> None:
    # backend on every record so unattended captures can tell a real TPU
    # profile from a CPU run (scripts/on_tunnel_return.sh only assembles
    # BENCH_SUITE_TPU.json from backend:"tpu" records)
    # ratios live near 1.0 where one decimal would erase the effect being
    # measured (the mesh_scale penalty A/B is a ~9% signal); rates keep
    # the compact one-decimal form
    digits = 4 if unit == "ratio" else 1
    record = {"suite": suite, "value": round(value, digits), "unit": unit,
              "backend": jax.default_backend(), **extra}
    print(json.dumps(record), flush=True)
    from sparse_coding_tpu.obs import ledger as perf_ledger

    _LEDGER["emitted"] += 1
    if perf_ledger.append_row({"kind": "suite", **record}):
        _LEDGER["appended"] += 1


def bench_ensemble(quick: bool) -> None:
    from bench import _time_ensemble  # single shared implementation

    d, ratio, n_members, batch = (256, 2, 8, 512) if quick else (512, 4, 32, 2048)
    steps, scan = (15, 5) if quick else (200, 10)
    # (matmul_precision governs only the autodiff path; Pallas kernel dots
    # take the bf16 MXU path via fused_compute_dtype instead)
    # tied family plus the untied FunctionalSAE family (the reference's
    # default SAE), each with its own fused kernel on TPU
    variants = [("autodiff", dict(use_fused=False)),
                ("untied_autodiff", dict(use_fused=False, sig="sae"))]
    if jax.default_backend() == "tpu":
        variants += [
            ("fused_two_stage", dict(use_fused=True,
                                     fused_path="two_stage")),
            ("fused_train_step", dict(use_fused=True,
                                      fused_path="train_step")),
            ("autodiff_bf16", dict(use_fused=False,
                                   matmul_precision="bfloat16")),
            ("fused_bf16", dict(use_fused=True,
                                fused_compute_dtype="bfloat16")),
            ("untied_fused_two_stage", dict(use_fused=True, sig="sae",
                                            fused_path="two_stage")),
            ("untied_fused_train_step", dict(use_fused=True, sig="sae",
                                             fused_path="train_step")),
            ("untied_fused_bf16", dict(use_fused=True, sig="sae",
                                       fused_compute_dtype="bfloat16")),
        ]
    for name, kwargs in variants:
        try:
            rate = _time_ensemble(d_act=d, n_dict=d * ratio,
                                  n_members=n_members, batch=batch,
                                  bench_steps=steps, scan_chunk=scan,
                                  **kwargs)
            _emit("ensemble_train", rate, "activations/s", variant=name,
                  n_members=n_members, d=d, n_dict=d * ratio, batch=batch)
        except Exception as e:
            print(f"ensemble variant {name} failed: {e!r}", file=sys.stderr)


def bench_ensemble_ratio(quick: bool) -> None:
    """Canonical-dict-ratio sweep (ISSUE 11): the paper's headline shapes
    live at ratios 16–96 (reference standard_metrics.py:745,
    big_sweep_experiments.py:543) — exactly where the untiled fused
    kernels used to fall back to autodiff silently. Per ratio this suite
    records WHICH kernel path auto mode resolved (plus the roofline plan
    at the canonical TPU scale) and the fused-vs-autodiff acts/s A/B.
    On a tunnel-down host it degrades per the bench conventions: a
    reduced-scale autodiff CPU measurement labeled backend "cpu", with
    the planned TPU path still recorded from the roofline model (pure
    host arithmetic), so the admission decision is auditable per round
    even without the chip."""
    from bench import _time_ensemble
    from sparse_coding_tpu.ops import roofline

    on_tpu = jax.default_backend() == "tpu"
    d = 256 if quick else 512
    ratios = (2, 4) if quick else (4, 8, 16, 32)
    # canonical TPU scale for the PLANNED-path record (what a sweep on
    # the chip would resolve); the measured scale shrinks off-chip
    plan_members, plan_batch = 8, 2048
    if on_tpu:
        n_members, batch, steps, scan = (4, 512, 6, 2) if quick \
            else (8, 2048, 40, 10)
    else:
        n_members, batch, steps, scan = (2, 256, 4, 2)
    for ratio in ratios:
        n_dict = d * ratio
        plan = roofline.choose_plan(
            n_members=plan_members, batch=plan_batch, n_feats=n_dict, d=d,
            family="tied")
        planned = plan.path or "autodiff"
        variants = [("autodiff", dict(use_fused=False))]
        if on_tpu:
            variants.insert(0, ("fused_auto", dict(use_fused="auto")))
        for name, kwargs in variants:
            try:
                rate = _time_ensemble(d_act=d, n_dict=n_dict,
                                      n_members=n_members, batch=batch,
                                      bench_steps=steps, scan_chunk=scan,
                                      **kwargs)
                _emit("ensemble_ratio", rate, "activations/s", variant=name,
                      ratio=ratio, d=d, n_dict=n_dict,
                      n_members=n_members, batch=batch,
                      resolved_path=getattr(rate, "fused_path", None)
                      or "autodiff",
                      planned_tpu_path=planned,
                      planned_tiles=[plan.batch_tile, plan.feat_tile])
            except Exception as e:
                print(f"ensemble_ratio ratio={ratio} variant {name} "
                      f"failed: {e!r}", file=sys.stderr)


def bench_big_sae(quick: bool) -> None:
    from sparse_coding_tpu.train.big_sae import init_big_sae, make_big_sae_step

    def run_shape(suite: str, d: int, n_feats: int, batch: int,
                  n_iters: int, variants, **extra) -> None:
        batch_data = jax.random.normal(jax.random.PRNGKey(1), (batch, d))
        for name, kwargs in variants:
            try:
                state, optimizer, l1 = init_big_sae(
                    jax.random.PRNGKey(0), d, n_feats, l1_alpha=1e-3,
                    n_worst=1024)
                step = make_big_sae_step(optimizer, l1, **kwargs)
                holder = {"state": state}

                def one():
                    holder["state"], metrics = step(holder["state"],
                                                    batch_data)
                    return metrics["loss"]

                rate = _timed(one, n_iters, batch)
                _emit(suite, rate, "activations/s", variant=name, d=d,
                      n_feats=n_feats, batch=batch, **extra)
            except Exception as e:
                # an autodiff OOM at the capacity shape is itself the
                # measurement: the kernel enables what XLA cannot allocate
                print(f"{suite} variant {name} failed: {e!r}",
                      file=sys.stderr)
                _emit(suite, 0.0, "activations/s", variant=name, d=d,
                      n_feats=n_feats, batch=batch, failed=repr(e)[:160],
                      **extra)

    d, n_feats, batch = (512, 4096, 4096) if quick else (1024, 16384, 16384)
    variants = [("autodiff", dict(use_fused=False))]
    if jax.default_backend() == "tpu":
        variants += [("fused", dict(use_fused=True)),
                     ("fused_bf16", dict(use_fused=True,
                                         fused_compute_dtype="bfloat16"))]
    run_shape("big_sae_train", d, n_feats, batch, 3 if quick else 15,
              variants)

    if jax.default_backend() == "tpu" and not quick:
        # capacity-bound shape (VERDICT r4 weak #4): the f32 codes matrix
        # alone is batch*n_feats*4 = 8.6 GB and autodiff materializes it
        # TWICE (value + cotangent) — past any 16 GB chip's HBM — while the
        # flash kernels never materialize it at all. This is the regime the
        # kernels exist for; auto mode gates on exactly this capacity
        # threshold (train/big_sae.py fused_auto_choice).
        run_shape("big_sae_train_capacity", 1024, 131072, 16384, 5,
                  [("autodiff", dict(use_fused=False)),
                   ("fused", dict(use_fused=True)),
                   ("fused_bf16", dict(use_fused=True,
                                       fused_compute_dtype="bfloat16"))],
                  codes_gb=round(16384 * 131072 * 4 / 1e9, 1))


def bench_harvest(quick: bool) -> None:
    from sparse_coding_tpu.data.harvest import make_harvest_fn
    from sparse_coding_tpu.lm import gptneox
    from sparse_coding_tpu.lm.model_config import get_config, tiny_test_config

    if quick:
        cfg = tiny_test_config("gptneox")
    else:
        cfg = get_config("EleutherAI/pythia-70m-deduped")
    params = gptneox.init_params(jax.random.PRNGKey(0), cfg)
    b, s = (8, 64) if quick else (8, 256)
    toks = jnp.asarray(np.random.default_rng(0).integers(
        0, cfg.vocab_size, (b, s)))
    taps = ("residual.2",) if not quick else ("residual.1",)
    fn = make_harvest_fn(params, cfg, taps, forward=gptneox.forward)
    rate = _timed(lambda: next(iter(fn(toks).values())), 3 if quick else 15,
                  b * s)
    _emit("harvest", rate, "tokens/s", d_model=cfg.d_model,
          n_layers=cfg.n_layers, context=s)

    # scan_batches A/B: K forwards per device program amortize the
    # ~54 ms/dispatch tunnel overhead exactly like training's scan_steps
    k = 4 if quick else 8
    fn_scan = make_harvest_fn(params, cfg, taps, forward=gptneox.forward,
                              scan_batches=k)
    stack = jnp.asarray(np.tile(np.asarray(toks)[None], (k, 1, 1)))
    rate = _timed(lambda: next(iter(fn_scan(stack).values())),
                  3 if quick else 15, k * b * s)
    _emit("harvest", rate, "tokens/s", variant=f"scan{k}",
          d_model=cfg.d_model, n_layers=cfg.n_layers, context=s)


def bench_chunk_io(quick: bool) -> None:
    import tempfile
    from pathlib import Path

    from sparse_coding_tpu.data.chunk_store import ChunkStore, ChunkWriter

    rows = 100_000 if quick else 1_000_000
    d = 512
    with tempfile.TemporaryDirectory() as td:
        w = ChunkWriter(td, d, chunk_size_gb=rows * d * 2 / 2**30,
                        dtype="float16")
        w.add(np.random.default_rng(0).standard_normal(
            (rows, d), dtype=np.float32).astype(np.float16))
        w.finalize()
        store = ChunkStore(td)
        file_bytes = store.chunk_paths[0].stat().st_size
        store.load_chunk(0)  # warm lazy imports (torch cast bridge) + cache
        t0 = time.perf_counter()
        store.load_chunk(0)
        dt = time.perf_counter() - t0
        # NOTE: warm page cache (file just written) — measures decode+cast
        # throughput, not cold-disk reads
        _emit("chunk_io", file_bytes / dt / 2**20,
              "MB/s (warm-cache read + f32 cast)", rows=rows, d=d)


def bench_ingest_soak(quick: bool) -> None:
    """Sharded-store async ingest soak (ISSUE 8): chunk→device throughput
    vs shard count × decode-stream count, with the per-stage walls read
    back through ``obs.report``'s ingest section (the production evidence
    path). The point to prove: with streams overlapping, the consumer's
    wall stops being decode-bound — ``decode_s`` (summed across streams)
    exceeds the wall it used to BE, i.e. the sweep goes compute-bound."""
    import tempfile

    from sparse_coding_tpu import obs
    from sparse_coding_tpu.data.chunk_store import ChunkWriter
    from sparse_coding_tpu.data.ingest import chunk_stream, device_batches
    from sparse_coding_tpu.data.shard_store import (
        build_store_manifest,
        open_store,
        shard_name,
        write_shard_digest,
    )
    from sparse_coding_tpu.obs.report import build_report

    d = 256 if quick else 512
    rows_per_chunk = 4096 if quick else 16384
    chunks_per_shard = 2
    shard_counts = (1, 2) if quick else (1, 2, 4)
    stream_counts = (1, 2) if quick else (1, 2, 4)
    rng = np.random.default_rng(0)
    for n_shards in shard_counts:
        with tempfile.TemporaryDirectory() as td:
            root = Path(td) / "store"
            for si in range(n_shards):
                w = ChunkWriter(root / shard_name(si), d,
                                chunk_size_gb=rows_per_chunk * d * 2 / 2**30,
                                dtype="float16")
                w.add(rng.standard_normal(
                    (rows_per_chunk * chunks_per_shard, d),
                    dtype=np.float32).astype(np.float16))
                w.finalize({"synthetic": True})
                write_shard_digest(root / shard_name(si))
            build_store_manifest(root, expect_shards=n_shards)
            n_chunks = open_store(root).n_chunks
            total_bytes = n_chunks * rows_per_chunk * d * 2
            order = list(range(n_chunks))
            for streams in stream_counts:
                # a FRESH store per config: ChunkStore caches digest
                # verification per chunk (_digest_verified), so a shared
                # instance would make the first config pay every sha256
                # and later ones skip them — biasing the comparison
                store = open_store(root)
                store.load_chunk(0)  # warm lazy imports + page cache
                with tempfile.TemporaryDirectory() as run_dir:
                    prev = obs.configure_sink(obs.EventSink(
                        Path(run_dir) / "obs" / "ingest.jsonl"))
                    t0 = time.perf_counter()
                    try:
                        # the sweep's exact feed: multi-stream decode →
                        # double-buffered device staging
                        for batch in device_batches(
                                c for c in chunk_stream(store, order,
                                                        streams=streams)
                                if c is not None):
                            jax.block_until_ready(batch)
                    finally:
                        dt = time.perf_counter() - t0
                        obs.flush_metrics()
                        obs.configure_sink(prev)
                    ing = build_report(run_dir)["ingest"]
                _emit("ingest_soak", total_bytes / dt / 2**20, "MB/s to device",
                      n_shards=n_shards, streams=streams, chunks=n_chunks,
                      rows_per_chunk=rows_per_chunk, d=d,
                      decode_s=round(ing["decode_s"], 3),
                      transfer_s=round(ing["transfer_s"], 3),
                      wall_s=round(dt, 3),
                      # >1.0 == decode overlapped past the wall: the
                      # consumer is no longer decode-bound
                      decode_overlap=round(ing["decode_s"] / dt, 2)
                      if dt else None)


def bench_streaming_eval(quick: bool) -> None:
    """Dataset-scale metric sweep over a multi-chunk ChunkStore (bounded
    memory): activations/s through n_ever_active + moment accumulation."""
    import tempfile

    from sparse_coding_tpu.data.chunk_store import ChunkStore, ChunkWriter
    from sparse_coding_tpu.metrics.core import streaming_eval_sweep
    from sparse_coding_tpu.models.sae import FunctionalTiedSAE

    # batch divides rows so the remainder-carry path processes every row and
    # the activations/s numerator is exact
    rows, d, ratio, bs = (60_000, 256, 2, 4000) if quick else (400_000, 512, 4, 4000)
    ld = FunctionalTiedSAE.to_learned_dict(
        *FunctionalTiedSAE.init(jax.random.PRNGKey(0), d, d * ratio,
                                l1_alpha=1e-3))
    with tempfile.TemporaryDirectory() as td:
        w = ChunkWriter(td, d, chunk_size_gb=(rows // 4) * d * 2 / 2**30,
                        dtype="float16")
        w.add(np.random.default_rng(0).standard_normal(
            (rows, d)).astype(np.float16))
        w.finalize()
        store = ChunkStore(td)
        # the numerator stays 2*rows (= one activation through EACH of the
        # two metric families) for comparability with earlier rounds; the
        # single_pass label records that the dataset is now read ONCE and
        # slab i+1's transfer overlaps slab i's scans (VERDICT r4 next #3)
        streaming_eval_sweep(ld, store, batch_size=bs)  # warmup compiles
        t0 = time.perf_counter()
        streaming_eval_sweep(ld, store, batch_size=bs)
        dt = time.perf_counter() - t0
        _emit("streaming_eval", 2 * rows / dt, "activations/s",
              n_chunks=store.n_chunks, d=d, n_feats=d * ratio,
              single_pass=True)

        # isolation A/B (VERDICT r3 weak #7): the same sweep from ONE slab
        # ALREADY ON DEVICE — no disk read, no f16 decode, no host->device
        # transfer inside the timed region (_iter_slabs' jnp.asarray is a
        # no-op on a device array). The gap streaming_eval vs
        # streaming_eval_ram is the whole chunk pipeline (disk + decode +
        # tunnel transfer); the gap streaming_eval_ram vs ensemble_train is
        # the eval path itself (encode-only compute + per-metric syncs).
        slab = jnp.asarray(np.random.default_rng(1).standard_normal(
            (rows, d), dtype=np.float32))
        jax.block_until_ready(slab)
        streaming_eval_sweep(ld, slab, batch_size=bs)  # warmup (recompile)
        t0 = time.perf_counter()
        streaming_eval_sweep(ld, slab, batch_size=bs)
        dt = time.perf_counter() - t0
        _emit("streaming_eval_ram", 2 * rows / dt, "activations/s",
              d=d, n_feats=d * ratio, single_pass=True)


def bench_guardian_soak(quick: bool) -> None:
    """Divergence-drill soak (ISSUE 10): three synthetic sweeps over one
    store — sentinel OFF (the pre-guardian step programs), sentinel ON
    (same data, no injection), and sentinel ON with a member-targeted NaN
    injected mid-sweep. Reports the sentinel's step overhead (ON vs OFF
    ``sweep.chunk`` span walls, read back through ``obs.report`` — the
    production evidence path; acceptance wants <2%), and proves the drill
    semantics at bench scale: exactly one member frozen, ZERO rollbacks
    (live members never pay for a neighbor's divergence)."""
    import shutil
    import tempfile

    from sparse_coding_tpu import obs
    from sparse_coding_tpu.config import SyntheticEnsembleArgs
    from sparse_coding_tpu.obs.report import build_report
    from sparse_coding_tpu.resilience import faults
    import sparse_coding_tpu.train.sweep as sweep_mod
    from sparse_coding_tpu.train.experiments import dense_l1_range_experiment

    d, members, rows = (64, 4, 60_000) if quick else (128, 8, 200_000)
    l1s = list(np.logspace(-4, -2, members))
    with tempfile.TemporaryDirectory() as td:
        root = Path(td)

        def cfg(name, sentinel):
            return SyntheticEnsembleArgs(
                output_folder=str(root / name),
                dataset_folder=str(root / "chunks"), batch_size=1024,
                n_chunks=4, activation_dim=d,
                n_ground_truth_features=2 * d, dataset_size=rows,
                learned_dict_ratio=2.0, sentinel=sentinel, seed=0)

        build = lambda c, m: dense_l1_range_experiment(  # noqa: E731
            c, m, l1_range=l1s, activation_dim=d)

        def run(name, sentinel, plan=None):
            run_dir = root / f"obs_{name}"
            prev_sink = obs.configure_sink(
                obs.EventSink(run_dir / "obs" / "soak.jsonl"))
            prev_reg = obs.set_registry(obs.Registry())
            try:
                if plan:
                    faults.install_plan(faults.parse_fault_plan(plan))
                sweep_mod.sweep(build, cfg(name, sentinel), log_every=10**9,
                                image_metrics_every=None)
                obs.flush_metrics()
            finally:
                faults.install_plan(None)
                obs.set_registry(prev_reg)
                obs.configure_sink(prev_sink)
            report = build_report(run_dir)
            # p50 chunk wall = steady state: chunk 0's wall carries the
            # step program's XLA compile, which at soak scale would drown
            # the per-step signal this scenario exists to measure
            chunk = report["spans"].get("sweep.chunk", {})
            return (chunk.get("p50_s") or 0.0, report["guardian"])

        run("warmup", sentinel=True)  # store materialization
        off_s, _ = run("off", sentinel=False)
        on_s, _ = run("on", sentinel=True)
        inj_s, guard = run(
            "inject", sentinel=True,
            plan=f"sweep.anomaly:nth=5,mode=error,message=member="
                 f"{members // 2}")
        overhead_pct = (on_s - off_s) / off_s * 100.0 if off_s else 0.0
        _emit("guardian_soak", overhead_pct, "% sentinel step overhead",
              n_members=members, d=d, rows=rows,
              chunk_p50_off=round(off_s, 4), chunk_p50_on=round(on_s, 4),
              chunk_p50_injected=round(inj_s, 4),
              frozen_members=guard["members_quarantined"],
              rollbacks=guard["rollbacks"], halts=guard["halts"])
        shutil.rmtree(root / "chunks", ignore_errors=True)


def bench_perf_probe(quick: bool) -> None:
    """Device-time probe overhead A/B (ISSUE 12 acceptance): two
    identical synthetic sweeps over one store — probe OFF
    (``perf_probe_every=0``, the pre-probe step loop) vs probe ON at the
    DEFAULT cadence — compared on steady-state ``sweep.chunk`` p50 walls
    read back through ``obs.report``. The acceptance bar is <2% overhead
    (the bracketed windows are 1-in-32; everything between them keeps
    full dispatch pipelining). The ON run's report must also show the
    perf section populated and backend-labeled: per-path MFU and the
    predicted-vs-achieved roofline gap — on this host that is the
    cpu-fallback labeling path the runbook documents."""
    import shutil
    import tempfile

    from sparse_coding_tpu import obs
    from sparse_coding_tpu.config import SyntheticEnsembleArgs
    from sparse_coding_tpu.obs.report import build_report
    import sparse_coding_tpu.train.sweep as sweep_mod
    from sparse_coding_tpu.train.experiments import dense_l1_range_experiment

    d, members, rows = (64, 4, 80_000) if quick else (128, 8, 240_000)
    l1s = list(np.logspace(-4, -2, members))
    with tempfile.TemporaryDirectory() as td:
        root = Path(td)

        def cfg(name, probe_every):
            return SyntheticEnsembleArgs(
                output_folder=str(root / name),
                dataset_folder=str(root / "chunks"), batch_size=1024,
                n_chunks=4, activation_dim=d,
                n_ground_truth_features=2 * d, dataset_size=rows,
                learned_dict_ratio=2.0, seed=0,
                perf_probe_every=probe_every)

        build = lambda c, m: dense_l1_range_experiment(  # noqa: E731
            c, m, l1_range=l1s, activation_dim=d)

        def run(name, probe_every):
            run_dir = root / f"obs_{name}"
            prev_sink = obs.configure_sink(
                obs.EventSink(run_dir / "obs" / "probe.jsonl"))
            prev_reg = obs.set_registry(obs.Registry())
            try:
                sweep_mod.sweep(build, cfg(name, probe_every),
                                log_every=10**9, image_metrics_every=None)
                obs.flush_metrics()
            finally:
                obs.set_registry(prev_reg)
                obs.configure_sink(prev_sink)
            report = build_report(run_dir)
            chunk = report["spans"].get("sweep.chunk", {})
            return (chunk.get("p50_s") or 0.0, report["perf"])

        run("warmup", 0)  # store materialization + compile warmth
        # interleaved min-of-two per arm: single p50-of-4-chunks reads
        # carry ±5-7% host noise (measured), which would drown the <2%
        # acceptance bar; the min of two interleaved passes is robust to
        # one-sided spikes without hiding a systematic cost
        off_s = min(run("off_a", 0)[0], run("off_b", 0)[0])
        on_a, perf = run("on_a", obs.perf.DEFAULT_PROBE_EVERY)
        on_s = min(on_a, run("on_b", obs.perf.DEFAULT_PROBE_EVERY)[0])
        overhead_pct = (on_s - off_s) / off_s * 100.0 if off_s else 0.0
        mfu_rows = perf.get("mfu", {})
        gap_rows = perf.get("roofline_gap", {})
        assert perf.get("samples", 0) >= 1, \
            "probe ON at default cadence took no samples"
        assert mfu_rows, "perf section has no MFU rows"
        assert any("backend=" in k for k in mfu_rows), \
            f"MFU rows are not backend-labeled: {sorted(mfu_rows)}"
        assert gap_rows, "perf section has no roofline-gap rows"
        _emit("perf_probe", overhead_pct, "% probe step overhead",
              n_members=members, d=d, rows=rows,
              cadence=obs.perf.DEFAULT_PROBE_EVERY,
              chunk_p50_off=round(off_s, 4), chunk_p50_on=round(on_s, 4),
              samples=perf.get("samples"),
              mfu={k: round(v, 4) for k, v in sorted(mfu_rows.items())},
              gap_p50={k: round(s["p50"], 3)
                       for k, s in sorted(gap_rows.items())})
        shutil.rmtree(root / "chunks", ignore_errors=True)


def bench_serving(quick: bool) -> None:
    """Online feature-extraction serving: concurrent mixed-size requests
    through the micro-batching engine's AOT bucket programs. Reports
    end-to-end throughput plus per-request p50/p99 latency and the
    steady-state recompile count (must be 0 — every recompile is a trace
    in the latency path)."""
    import threading

    from sparse_coding_tpu.models.sae import FunctionalTiedSAE
    from sparse_coding_tpu.serve import ModelRegistry, ServingEngine

    d, ratio = (256, 2) if quick else (512, 4)
    n_threads, per_thread = (4, 50) if quick else (8, 250)
    ld = FunctionalTiedSAE.to_learned_dict(
        *FunctionalTiedSAE.init(jax.random.PRNGKey(0), d, d * ratio,
                                l1_alpha=1e-3))
    registry = ModelRegistry()
    registry.register("sae", ld)
    rng = np.random.default_rng(0)
    sizes = rng.integers(1, 65, n_threads * per_thread)
    payloads = [np.asarray(rng.standard_normal((int(s), d)), np.float32)
                for s in sizes]
    with ServingEngine(registry, max_wait_ms=1.0,
                       max_queue_rows=1 << 20) as engine:
        engine.warmup()

        def submitter(tid: int) -> None:
            futures = [engine.submit("sae", payloads[tid * per_thread + i])
                       for i in range(per_thread)]
            for f in futures:
                f.result(timeout=120)

        threads = [threading.Thread(target=submitter, args=(t,))
                   for t in range(n_threads)]
        t0 = time.perf_counter()
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        dt = time.perf_counter() - t0
        snap = engine.stats()
    total_rows = int(sizes.sum())
    fill = (sum(b["rows"] for b in snap["buckets"].values())
            / max(1, sum(b["batches"] * size
                         for size, b in snap["buckets"].items())))
    _emit("serving", total_rows / dt, "activations/s",
          n_requests=len(payloads), n_threads=n_threads, d=d,
          n_feats=d * ratio,
          p50_ms=round(snap["p50_ms"], 3) if snap["p50_ms"] else None,
          p99_ms=round(snap["p99_ms"], 3) if snap["p99_ms"] else None,
          fill_ratio=round(fill, 3), recompiles=snap["recompiles"])


def bench_gateway(quick: bool, variant: str | None = None) -> None:
    """Mixed-tenant gateway soak (ISSUE 6 / ROADMAP item 2; ladder
    variants ISSUE 20): three priority classes from concurrent tenants
    through a replica pool with hedging live — including a
    feature-catalog tenant (ISSUE 16) firing interactive top-k
    ``neighbors`` requests into the SAME pool as the encode tenants —
    under a SKEWED request-size mix that pads badly on the static
    ladder. Two variants, each its own ledger row: ``static_ladder``
    (fixed buckets, no rebatching) and ``derived_ladder`` (continuous
    rebatching on, traffic-derived ladder swapped in mid-stream through
    ``maybe_swap_ladder`` — the zero-compile path). Reported per
    variant: throughput, ``ttfr_s`` (construction→first result wall),
    ``wasted_pad_rows`` over the measured soak, p50/p95/p99 request
    latency read back from a merged ``obs.report`` (the production
    evidence path, not an ad-hoc timer), sheds, hedge accounting, and
    the steady-state compile count — which must be 0: after warmup (and
    after the ladder swap), no request may ever pay a trace or compile
    in the latency path."""
    variants = (variant,) if variant else ("static_ladder",
                                           "derived_ladder")
    for v in variants:
        _gateway_soak_variant(quick, v)


def _gateway_soak_variant(quick: bool, variant: str) -> None:
    import tempfile
    import threading

    from sparse_coding_tpu import obs
    from sparse_coding_tpu.models.sae import FunctionalTiedSAE
    from sparse_coding_tpu.obs.report import build_report
    from sparse_coding_tpu.serve import (
        DEFAULT_OPS,
        INTERACTIVE,
        PRIORITIES,
        ModelRegistry,
        QueueFullError,
        ServingGateway,
    )

    if variant not in ("static_ladder", "derived_ladder"):
        raise ValueError(f"unknown gateway_soak variant {variant!r} "
                         "(choose static_ladder | derived_ladder)")
    derived = variant == "derived_ladder"
    d, ratio = (256, 2) if quick else (512, 4)
    n_threads, per_thread = (3, 40) if quick else (6, 150)
    ld = FunctionalTiedSAE.to_learned_dict(
        *FunctionalTiedSAE.init(jax.random.PRNGKey(0), d, d * ratio,
                                l1_alpha=1e-3))
    registry = ModelRegistry()
    registry.register("sae", ld)
    rng = np.random.default_rng(0)
    # skewed request-size mix (ISSUE 20): ~85% cluster just above the
    # static ladder's smallest rung — every one pads 18-30 rows up to 64
    # on (8, 64, 512) — plus a mid-size tail that pads up to 512. The
    # shape a derived ladder earns its keep on; same mix for BOTH
    # variants so the rows compare.
    n_req = n_threads * per_thread
    small = rng.integers(18, 31, n_req)
    large = rng.integers(200, 281, n_req)
    sizes = np.where(rng.random(n_req) < 0.85, small, large)
    payloads = [np.asarray(rng.standard_normal((int(s), d)), np.float32)
                for s in sizes]
    # the catalog tenant's feature-intelligence requests (ISSUE 16):
    # top-k decoder-row similarity through the same pool, so the soak
    # exercises mixed encode+neighbors flushes under priority pressure
    cat_per_thread = per_thread // 2
    cat_payloads = [np.asarray(rng.standard_normal((int(s), d)), np.float32)
                    for s in rng.integers(18, 31, cat_per_thread)]
    # prime traffic: replayed before the measured soak to feed the
    # request-size histogram the derivation snapshots
    prime = payloads[:max(8, n_req // 8)]
    obs.install_jax_probes()
    t_start = time.perf_counter()
    with ServingGateway(registry, n_replicas=2, n_spares=1,
                        max_wait_ms=1.0, max_queue_rows=1 << 20,
                        hedge_min_samples=64,
                        ops=tuple(DEFAULT_OPS) + ("neighbors",),
                        rebatch=derived, ladder_hold_ticks=1,
                        engine_kwargs={"topk_k": 8}) as gw:
        gw.warmup()
        # ttfr: construction + warmup + one real request resolved
        gw.submit("sae", payloads[0]).result(timeout=120)
        ttfr_s = time.perf_counter() - t_start
        for p in prime:
            gw.submit("sae", p).result(timeout=120)
        swap = gw.maybe_swap_ladder() if derived else None
        # pad/compile baselines AFTER the swap: wasted_pad_rows and
        # steady_compiles measure the soak on the ladder that serves it
        compiles0 = obs.counter("jax.compiles").value

        def _pad_state() -> tuple:
            bk = gw.stats()["buckets"]
            return (sum(b["batches"] * size for size, b in bk.items()),
                    sum(b["rows"] for b in bk.values()))

        cap0, rows0 = _pad_state()

        def submitter(tid: int) -> None:
            prio = PRIORITIES[tid % len(PRIORITIES)]
            futures = []
            for i in range(per_thread):
                try:
                    futures.append(gw.submit(
                        "sae", payloads[tid * per_thread + i],
                        priority=prio))
                except QueueFullError:
                    pass  # a handled shed; counted by the gateway
            for f in futures:
                f.result(timeout=120)

        def catalog_tenant() -> None:
            futures = []
            for p in cat_payloads:
                try:
                    futures.append(gw.submit("sae", p, op="neighbors",
                                             priority=INTERACTIVE))
                except QueueFullError:
                    pass
            for f in futures:
                f.result(timeout=120)

        threads = [threading.Thread(target=submitter, args=(t,))
                   for t in range(n_threads)]
        threads.append(threading.Thread(target=catalog_tenant))
        t0 = time.perf_counter()
        for th in threads:
            th.start()
        for th in threads:
            th.join()
        dt = time.perf_counter() - t0
        steady_compiles = obs.counter("jax.compiles").value - compiles0
        cap1, rows1 = _pad_state()
        active_rungs = list(gw.active_buckets)
        snap = gw.stats()
        # latency quantiles via the production evidence path: flush the
        # gateway registry into an event file, merge with obs.report
        with tempfile.TemporaryDirectory() as run_dir:
            prev = obs.configure_sink(obs.EventSink(
                Path(run_dir) / "obs" / "gateway.jsonl"))
            try:
                obs.flush_metrics(registry=gw.metrics.registry)
            finally:
                obs.configure_sink(prev)
            report = build_report(run_dir)
        lat = report["histograms"].get("gateway.latency_s", {})
    # throughput counts the rows actually served during the measured
    # soak (sheds and prime traffic excluded); wasted pad likewise
    soak_rows = rows1 - rows0
    wasted_pad_rows = (cap1 - cap0) - soak_rows
    g = snap["gateway"]
    _emit("gateway_soak", soak_rows / dt, "activations/s",
          variant=variant,
          n_requests=len(payloads) + len(cat_payloads),
          catalog_requests=len(cat_payloads), n_threads=n_threads + 1,
          d=d, n_replicas=2,
          ttfr_s=round(ttfr_s, 3),
          wasted_pad_rows=int(wasted_pad_rows),
          ladder_rungs=active_rungs, ladder_swapped=swap is not None,
          rebatch_joined=snap["rebatch"]["joined"],
          rebatch_joined_rows=snap["rebatch"]["joined_rows"],
          p50_ms=(round(lat["p50"] * 1e3, 3) if lat.get("p50") else None),
          p95_ms=(round(lat["p95"] * 1e3, 3) if lat.get("p95") else None),
          p99_ms=(round(lat["p99"] * 1e3, 3) if lat.get("p99") else None),
          shed=sum(g["shed"].values()),
          hedges_fired=g["hedges_fired"], hedges_won=g["hedges_won"],
          failovers=g["failovers"],
          recompiles=snap["recompiles"], steady_compiles=steady_compiles)


def bench_catalog(quick: bool) -> None:
    """Feature-catalog scenario (ISSUE 16): (a) the index build wall —
    the jax-free compile of per-feature stats + cross-dict matches over
    a synthetic sweep artifact and chunk store (catalog/build.py; safe
    under a wedged tunnel) — and (b) concurrent top-k neighbor queries
    through the REAL gateway query path (SLO admission, micro-batched
    AOT ``neighbors`` bucket programs riding the catalog request
    classes), with p50/p99 request latency read back from a merged
    ``obs.report`` (the production evidence path). Off TPU both rows
    are labeled ``cpu-fallback`` — ranking evidence for the on-chip
    round, not wall-clock truth."""
    import tempfile
    import threading

    from sparse_coding_tpu import obs
    from sparse_coding_tpu.catalog import (
        CatalogIndex,
        CatalogService,
        build_catalog,
    )
    from sparse_coding_tpu.data.chunk_store import ChunkWriter
    from sparse_coding_tpu.models.sae import FunctionalTiedSAE
    from sparse_coding_tpu.obs.report import build_report
    from sparse_coding_tpu.serve import ModelRegistry, ServingGateway
    from sparse_coding_tpu.utils.artifacts import (
        load_learned_dicts,
        save_learned_dicts,
    )

    on_tpu = jax.default_backend() == "tpu"
    backend_label = jax.default_backend() if on_tpu else "cpu-fallback"
    d, ratio, n_dicts = (64, 4, 3) if quick else (128, 8, 4)
    rows = 40_000 if quick else 200_000
    n_threads, per_thread = (2, 40) if quick else (4, 150)
    k = 8 if quick else 16
    n_feats = d * ratio
    rng = np.random.default_rng(0)
    with tempfile.TemporaryDirectory() as td:
        base = Path(td)
        w = ChunkWriter(base / "chunks", d,
                        chunk_size_gb=(rows // 4) * d * 2 / 2**30,
                        dtype="float16")
        w.add(rng.standard_normal((rows, d), dtype=np.float32)
              .astype(np.float16))
        w.finalize()
        pkl = base / "sweep" / "learned_dicts.pkl"
        save_learned_dicts(
            [(FunctionalTiedSAE.to_learned_dict(
                *FunctionalTiedSAE.init(jax.random.PRNGKey(i), d, n_feats,
                                        l1_alpha=1e-3)),
              {"l1_alpha": 1e-3, "seed": i}) for i in range(n_dicts)],
            pkl)
        t0 = time.perf_counter()
        build_catalog(pkl, base / "chunks", base / "cat",
                      experiment="bench")
        build_wall = time.perf_counter() - t0
        _emit("catalog", build_wall, "s", variant="build",
              backend=backend_label, rows=rows, n_dicts=n_dicts, d=d,
              n_feats=n_feats,
              **({} if on_tpu
                 else {"note": "host-side build on a cpu-fallback run"}))

        index = CatalogIndex.load(base / "cat", verify=True)
        reg = ModelRegistry()
        names = reg.load_native(pkl, prefix="cat")
        reg.register_stack("cat/stack",
                           [ld for ld, _ in load_learned_dicts(pkl)])
        feats = rng.integers(0, n_feats, n_threads * per_thread)
        obs.install_jax_probes()
        with ServingGateway(reg, n_replicas=1, n_spares=0, buckets=(8,),
                            ops=("neighbors", "vote"), max_wait_ms=1.0,
                            engine_kwargs={"topk_k": k}) as gw:
            gw.warmup()
            svc = CatalogService(index, gw, models=names,
                                 stack_model="cat/stack")

            def submitter(tid: int) -> None:
                for i in range(per_thread):
                    svc.neighbors(tid % n_dicts,
                                  int(feats[tid * per_thread + i]))

            threads = [threading.Thread(target=submitter, args=(t,))
                       for t in range(n_threads)]
            t0 = time.perf_counter()
            for th in threads:
                th.start()
            for th in threads:
                th.join()
            dt = time.perf_counter() - t0
            # latency quantiles via the production evidence path: flush
            # the gateway registry into an event file, merge via report
            with tempfile.TemporaryDirectory() as run_dir:
                prev = obs.configure_sink(obs.EventSink(
                    Path(run_dir) / "obs" / "catalog.jsonl"))
                try:
                    obs.flush_metrics(registry=gw.metrics.registry)
                finally:
                    obs.configure_sink(prev)
                report = build_report(run_dir)
            lat = report["histograms"].get("gateway.latency_s", {})
        n_q = n_threads * per_thread
        _emit("catalog", n_q / dt, "queries/s", variant="query",
              backend=backend_label, n_queries=n_q, k=k,
              n_dicts=n_dicts, d=d, n_feats=n_feats,
              p50_ms=(round(lat["p50"] * 1e3, 3) if lat.get("p50")
                      else None),
              p99_ms=(round(lat["p99"] * 1e3, 3) if lat.get("p99")
                      else None),
              **({} if on_tpu
                 else {"note": "cpu-fallback queries — ranking "
                               "evidence only"}))


def bench_fleet_soak(quick: bool) -> None:
    """Two-tenant fleet soak (ISSUE 14): two identical healthy tenants
    through the REAL scheduler — per-run worker subprocesses, one shared
    xcache — measuring (a) whole-fleet training throughput and (b) the
    number production cares about at tenant scale: TIME-TO-FIRST-STEP
    for tenant B, i.e. how long the second tenant waits from fleet start
    until its FIRST step child spawns (queue wait + tenant A's run on
    this serial container; on a pod with free slices it is ~placement
    latency — B's own pipeline work is excluded by construction). Worker children are ALWAYS cpu-pinned with the axon plugin
    stripped (the bench process may own the tunnel; a worker's jax child
    must never be the second tunnel-touching process — CLAUDE.md), so
    the row is labeled ``worker_backend: cpu`` whatever the bench
    backend. Also records tenant B's executable-store misses — 0 means
    the shared-cache warm start held at soak scale."""
    import shutil
    import tempfile
    import time as _time

    from sparse_coding_tpu.obs.report import build_fleet_report
    from sparse_coding_tpu.pipeline import FleetScheduler, RunJournal

    d, rows = (16, 2048) if quick else (32, 16384)
    with tempfile.TemporaryDirectory() as td:
        root = Path(td)

        def tenant_config(name):
            base = root / "fleet" / "runs" / name / "data"
            return {
                "harvest": {"mode": "synthetic",
                            "dataset_folder": str(base / "chunks"),
                            "activation_dim": d,
                            "n_ground_truth_features": 2 * d,
                            "feature_num_nonzero": 5,
                            "feature_prob_decay": 0.99,
                            "dataset_size": rows, "n_chunks": 4,
                            "batch_rows": 512, "seed": 0},
                "sweep": {"experiment": "dense_l1_range",
                          "ensemble": {"output_folder": str(base / "sweep"),
                                       "dataset_folder": str(base / "chunks"),
                                       "batch_size": 128, "n_chunks": 4,
                                       "learned_dict_ratio": 2.0,
                                       "tied_ae": True,
                                       "checkpoint_every_chunks": 2,
                                       "seed": 0},
                          "log_every": 10 ** 9},
                "eval": {"output_folder": str(base / "eval"),
                         "n_eval_rows": 512, "seed": 0},
            }

        sched = FleetScheduler(root / "fleet", n_slices=1,
                               max_concurrent=1, poll_s=0.05,
                               max_wall_s=1800)
        cpu_env = {"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": None}
        for name in ("tenant-a", "tenant-b"):
            sched.enqueue(name, tenant_config(name), env=cpu_env)
        t0_wall, t0 = _time.time(), _time.perf_counter()
        summary = sched.run()
        wall = _time.perf_counter() - t0

        # time-to-first-step: fleet start -> tenant B's FIRST step spawn
        # (its harvest) — pure queue wait + placement latency, with B's
        # own pipeline work excluded by construction
        b_journal = RunJournal(root / "fleet" / "runs" / "tenant-b"
                               / "journal.jsonl")
        spawns = [r["ts"] for r in b_journal.records()
                  if r["event"] == "step.spawn"]
        tts_b = (min(spawns) - t0_wall) if spawns else None
        fleet = build_fleet_report(root / "fleet")
        b_cc = fleet["tenants"]["tenant-b"]["report"]["compile_cache"]
        _emit("fleet_soak", 2 * rows / wall, "activations/s",
              tenants=2, d=d, rows_per_tenant=rows,
              states=summary, worker_backend="cpu",
              time_to_first_step_b_s=(round(tts_b, 3)
                                      if tts_b is not None else None),
              store_misses_b=b_cc["store_misses"],
              store_hits_b=b_cc["store_hits"],
              placements=fleet["scheduler"]["placements"])
        shutil.rmtree(root / "fleet", ignore_errors=True)


def bench_group_sae(quick: bool) -> None:
    """Group-SAE cost curve (ISSUE 19): G grouped tenants vs L per-layer
    baseline tenants through the REAL fleet scheduler, same per-SAE
    training budget — the paper's claim is that pooling adjacent layers
    cuts sweep cost by ~G/L at comparable FVU (arXiv 2410.21508), so the
    row reports the measured wall speedup AND both arms' aggregate FVU.
    The multi-tap store is harvested in-process (this bench process is
    the one jax process); each group tenant samples its pool at one
    layer's chunk budget (the paper's fixed-budget comparison — noted on
    the row). Worker children are ALWAYS cpu-pinned with the axon plugin
    stripped (CLAUDE.md: a worker's jax child must never be the second
    tunnel-touching process), so the row is labeled
    ``worker_backend: cpu`` whatever the bench backend."""
    import shutil
    import tempfile
    import time as _time

    from sparse_coding_tpu.data.shard_store import shard_name
    from sparse_coding_tpu.groups import group_tenant_config, load_groups
    from sparse_coding_tpu.pipeline import FleetScheduler
    from sparse_coding_tpu.pipeline.steps import (
        run_group,
        run_group_harvest,
        run_store_manifest,
    )

    d, rows, n_layers, n_groups = ((16, 1024, 4, 2) if quick
                                   else (32, 4096, 6, 2))
    per_layer_chunks = 4
    with tempfile.TemporaryDirectory() as td:
        root = Path(td)
        store = root / "store"
        cfg = {"harvest": {"mode": "synthetic",
                           "dataset_folder": str(store),
                           "layers": list(range(n_layers)),
                           "activation_dim": d,
                           "n_ground_truth_features": 2 * d,
                           "feature_num_nonzero": 5,
                           "feature_prob_decay": 0.99,
                           "dataset_size": rows,
                           "n_chunks": per_layer_chunks,
                           "batch_rows": 512, "seed": 0,
                           "phase_step": 0.35},
               "group": {"n_groups": n_groups, "n_sample_chunks": 2,
                         "n_sample_rows": 512, "seed": 0}}
        for i in range(n_layers):
            run_group_harvest(cfg, i)
        run_store_manifest(cfg)
        run_group(cfg)
        payload = load_groups(store)

        def sweep_eval(data_dir: str, out: Path) -> dict:
            return {
                "harvest": {"dataset_folder": data_dir},
                "sweep": {"experiment": "dense_l1_range",
                          "ensemble": {"output_folder": str(out / "sweep"),
                                       "dataset_folder": data_dir,
                                       "batch_size": 128,
                                       "n_chunks": per_layer_chunks,
                                       "learned_dict_ratio": 2.0,
                                       "tied_ae": True,
                                       "checkpoint_every_chunks": 2,
                                       "seed": 0},
                          "log_every": 10 ** 9},
                "eval": {"output_folder": str(out / "eval"),
                         "n_eval_rows": 512, "seed": 0},
            }

        cpu_env = {"JAX_PLATFORMS": "cpu", "PALLAS_AXON_POOL_IPS": None}

        def run_arm(fleet_dir: Path, tenants: list) -> tuple[float, float]:
            sched = FleetScheduler(fleet_dir, n_slices=1, max_concurrent=1,
                                   poll_s=0.05, max_wall_s=1800)
            for name, tcfg, kind in tenants:
                sched.enqueue(name, tcfg, kind=kind, env=cpu_env)
            t0 = _time.perf_counter()
            sched.run()
            wall = _time.perf_counter() - t0
            fvus = []
            for name, tcfg, _ in tenants:
                ev = json.loads((Path(tcfg["eval"]["output_folder"])
                                 / "eval.json").read_text())
                fvus.append(min(r["fvu"] for r in ev["dicts"]))
            return wall, float(np.mean(fvus))

        group_tenants = []
        base = sweep_eval(str(store), root / "unused")
        for g in payload["groups"]:
            tcfg = group_tenant_config(base, g, store, root / "grouped")
            # the paper's fixed-budget comparison: each group SAE trains
            # one layer's chunk budget sampled from its pool, not G×
            tcfg["sweep"]["ensemble"]["n_chunks"] = per_layer_chunks
            group_tenants.append((g["name"], tcfg, "group"))
        group_wall, group_fvu = run_arm(root / "fleet_g", group_tenants)

        layer_tenants = []
        for i in range(n_layers):
            sd = str(store / shard_name(i))
            layer_tenants.append(
                (f"layer-{i}", sweep_eval(sd, root / "baseline" / str(i)),
                 "flat"))
        base_wall, base_fvu = run_arm(root / "fleet_l", layer_tenants)

        _emit("group_sae", base_wall / group_wall, "x_speedup",
              variant=f"g{n_groups}_of_l{n_layers}",
              n_layers=n_layers, n_groups=n_groups, d=d,
              rows_per_layer=rows, group_wall_s=round(group_wall, 3),
              baseline_wall_s=round(base_wall, 3),
              fvu_group=round(group_fvu, 4),
              fvu_baseline=round(base_fvu, 4),
              worker_backend="cpu",
              note="fixed per-SAE chunk budget; group arm samples each "
                   "pool at one layer's budget (paper's G/L comparison)")
        shutil.rmtree(root, ignore_errors=True)


def bench_plane_tide(quick: bool) -> None:
    """Elastic-plane tide cycle (ISSUE 17): a real gateway + real fleet
    scheduler under one ElasticPlane arbiter, through a full tide —
    traffic ramp, scale-up that SIGTERM-reclaims a live scavenger sweep
    and activates a warm spare, drain, ebb, scale-down, sweep resume.
    Reports the number serving cares about — client-observed
    INTERACTIVE p99 across the ramp-and-scale-up window — plus the two
    elasticity walls (reclaim: up-rebalance → scavenger checkpointed
    out; resume: down-rebalance → sweep finished) and the steady-state
    compile count across the whole cycle (0 = the spare came off the
    xcache warmup manifest; anything else is the §13 regression this
    row exists to catch). The scavenger child is a jax-free command
    worker, so the scenario admits exactly ONE jax process (this one —
    CLAUDE.md) and is safe under a wedged tunnel."""
    import shutil
    import tempfile
    import threading
    import time as _time

    from sparse_coding_tpu import obs, xcache
    from sparse_coding_tpu.models import UntiedSAE
    from sparse_coding_tpu.pipeline import FleetScheduler
    from sparse_coding_tpu.pipeline.fleet_queue import (
        QUEUE_NAME,
        FleetQueue,
    )
    from sparse_coding_tpu.pipeline.plane import ElasticPlane, PlaneConfig
    from sparse_coding_tpu.serve import ModelRegistry, ServingGateway
    from sparse_coding_tpu.serve.slo import INTERACTIVE, SCAVENGER

    d, n, burst, steps = (32, 64, 48, 60) if quick else (64, 256, 160, 200)
    rng = jax.random.PRNGKey(7)
    k1, k2, k3 = jax.random.split(rng, 3)
    reg = ModelRegistry()
    reg.register("tide", UntiedSAE(
        encoder=jax.random.normal(k1, (n, d), jnp.float32),
        encoder_bias=jax.random.normal(k2, (n,), jnp.float32),
        dictionary=jax.random.normal(k3, (n, d), jnp.float32)))
    nrng = np.random.default_rng(11)
    payloads = [nrng.normal(size=(8, d)).astype(np.float32)
                for _ in range(burst)]

    scav_body = (
        "import json, pathlib, signal, sys, time\n"
        "state = pathlib.Path(sys.argv[1]); out = pathlib.Path(sys.argv[2])\n"
        "flag = []\n"
        "signal.signal(signal.SIGTERM, lambda *a: flag.append(1))\n"
        "vals = json.loads(state.read_text()) if state.exists() else []\n"
        f"while len(vals) < {steps}:\n"
        "    vals.append(len(vals))\n"
        "    time.sleep(0.02)\n"
        "    if flag:\n"
        "        state.write_text(json.dumps(vals)); sys.exit(75)\n"
        "out.write_text(json.dumps(vals)); sys.exit(0)\n")

    with tempfile.TemporaryDirectory() as td:
        root = Path(td)
        fleet_dir = root / "fleet"
        xcache.enable(root / "xc")
        try:
            with ServingGateway(reg, n_replicas=1, n_spares=1,
                                buckets=(8,), ops=("encode",),
                                max_wait_ms=0.5) as gw:
                gw.warmup()
                for p in payloads[:4]:
                    gw.query("tide", p, priority=INTERACTIVE, timeout=60)

                sched = FleetScheduler(fleet_dir, n_slices=1, poll_s=0.05,
                                       max_wall_s=600)
                plane = ElasticPlane(
                    fleet_dir,
                    PlaneConfig(n_slices=2, min_replicas=1, max_replicas=2,
                                up_queued_rows=4.0, down_queued_rows=2.0,
                                hold_ticks=2),
                    gateway=gw, fleet=sched)
                plane.reconcile()
                sched.enqueue("scav", priority=SCAVENGER, kind="command",
                              argv=[sys.executable, "-c", scav_body,
                                    str(root / "scav.ckpt"),
                                    str(root / "scav.out")],
                              done_path=root / "scav.out")
                summary: dict = {}
                worker = threading.Thread(
                    target=lambda: summary.update(sched.run()),
                    daemon=True)
                t_fleet = _time.perf_counter()
                worker.start()
                queue = FleetQueue(fleet_dir / QUEUE_NAME)
                deadline = _time.perf_counter() + 60
                while queue.replay().runs["scav"].state != "placed" \
                        and _time.perf_counter() < deadline:
                    _time.sleep(0.02)

                compiles0 = obs.counter("jax.compiles").value
                # ---- ramp: hold the dispatcher, pile the burst, let
                # the plane confirm an up move, then serve it all wide
                gw.pause()
                t_sub, futs = [], []
                for p in payloads[4:]:
                    t_sub.append(_time.perf_counter())
                    futs.append(gw.submit("tide", p,
                                          priority=INTERACTIVE))
                plane.tick()
                t_up = _time.perf_counter()
                up = plane.tick()
                gw.resume()
                lat_ms = []
                for t0, f in zip(t_sub, futs):
                    f.result(timeout=120)
                    lat_ms.append((_time.perf_counter() - t0) * 1e3)
                p99_ms = float(np.percentile(lat_ms, 99))
                # reclaim wall: up-rebalance -> sweep checkpointed out
                while queue.replay().runs["scav"].state != "queued" \
                        and _time.perf_counter() < deadline:
                    _time.sleep(0.02)
                reclaim_s = _time.perf_counter() - t_up

                # ---- ebb: EWMA decays, plane hands the slice back
                t_down = None
                for _ in range(200):
                    out = plane.tick()
                    if out["split"].serve_slices == 1:
                        t_down = _time.perf_counter()
                        break
                    _time.sleep(0.02)
                plane.tick()  # drain window: replica back to spare
                worker.join(timeout=600)
                t_end = _time.perf_counter()
                resume_s = (t_end - t_down
                            if t_down is not None else None)
                # useful steps per wall: every step the sweep completed
                # (checkpointed steps count — the reclaim is a pause,
                # not a loss) over its whole tide-interrupted residency
                scav_steps_s = steps / (t_end - t_fleet)
                steady_compiles = obs.counter("jax.compiles").value \
                    - compiles0
                planes = [r for r in queue.journal.records()
                          if r["event"] == "plane.rebalance"]
            _emit("plane_tide", p99_ms, "ms",
                  variant="ramp_scaleup_p99", d=d, burst=burst,
                  scaled_up=bool(up["rebalanced"]),
                  rebalances=len(planes),
                  reclaim_s=round(reclaim_s, 3),
                  resume_s=(round(resume_s, 3)
                            if resume_s is not None else None),
                  scav_steps_per_s=round(scav_steps_s, 2),
                  steady_compiles=steady_compiles,
                  states=summary, worker_backend="cpu")
        finally:
            xcache.disable()
        shutil.rmtree(fleet_dir, ignore_errors=True)


def bench_fsck_scan(quick: bool) -> None:
    """Durable-state audit scenario (ISSUE 18): full-verification fsck
    throughput over a synthetic run tree — a real ChunkWriter store
    (every chunk digest recomputed), leases, and torn-tail-checked
    event streams. The audit is host-side and jax-free by construction
    (the operator's wedged-tunnel tool), so off TPU the row is labeled
    ``cpu-fallback`` only to keep the ledger gate from diffing it
    against an on-chip round — the number itself is wall-clock truth
    on this host either way."""
    import tempfile

    from sparse_coding_tpu.data.chunk_store import ChunkWriter
    from sparse_coding_tpu.fsck import scan_tree
    from sparse_coding_tpu.resilience.lease import seed_lease

    on_tpu = jax.default_backend() == "tpu"
    backend_label = jax.default_backend() if on_tpu else "cpu-fallback"
    d, rows = (64, 32_768) if quick else (128, 262_144)
    rng = np.random.default_rng(0)
    with tempfile.TemporaryDirectory() as td:
        base = Path(td)
        w = ChunkWriter(base / "chunks", d,
                        chunk_size_gb=(rows // 8) * d * 2 / 2**30,
                        dtype="float16")
        w.add(rng.standard_normal((rows, d), dtype=np.float32)
              .astype(np.float16))
        w.finalize()
        seed_lease(base / "leases" / "bench.json", pid=os.getpid())
        (base / "events.jsonl").write_bytes(
            b"".join(json.dumps({"seq": i}).encode() + b"\n"
                     for i in range(2000)))
        n_bytes = sum(p.stat().st_size for p in base.rglob("*")
                      if p.is_file())
        scan_tree(base)  # warm the page cache: time digesting, not disk
        t0 = time.perf_counter()
        report = scan_tree(base)
        wall = time.perf_counter() - t0
        assert report.clean, [f"{f.path}: {f.detail}"
                              for f in report.findings]
        _emit("fsck_scan", n_bytes / wall / 2**20, "MB/s",
              variant="full_verify", backend=backend_label,
              n_files=sum(1 for p in base.rglob("*") if p.is_file()),
              tree_mb=round(n_bytes / 2**20, 2), wall_s=round(wall, 4),
              **({} if on_tpu
                 else {"note": "host-side audit on a cpu-fallback run"}))


def bench_mesh_scale(quick: bool) -> None:
    """ISSUE 15 scenario: whole-step vs two-stage fused A/B at 1 device
    and on the ("model", "data") mesh spanning every visible device —
    the two-stage-multi-chip-penalty-gone acceptance measurement. Off
    TPU the kernels run interpret-mode on the
    --xla_force_host_platform_device_count CPU mesh and every row is
    labeled ``cpu-fallback`` (ranking evidence, not wall-clock). Each
    config's device-time samples ride a DeviceStepProbe with the mesh
    shape folded into the path label, so the per-mesh-shape MFU and the
    RESOLVED kernel path are read back through obs.report — the emitted
    rows carry what the report computed, not a side channel."""
    import dataclasses
    import tempfile

    from sparse_coding_tpu import obs
    from sparse_coding_tpu.ensemble import Ensemble
    from sparse_coding_tpu.models.sae import FunctionalTiedSAE
    from sparse_coding_tpu.obs.report import build_report
    from sparse_coding_tpu.parallel.mesh import make_mesh

    on_tpu = jax.default_backend() == "tpu"
    backend_label = jax.default_backend() if on_tpu else "cpu-fallback"
    d, n_dict, n_members = (32, 64, 4) if quick else (64, 256, 8)
    steps = 4 if quick else 20
    n_dev = len(jax.devices())
    meshes = [("1x1", make_mesh(1, 1))]
    if n_dev >= 8:
        meshes.append(("2x4", make_mesh(2, 4)))
    elif n_dev > 1:
        meshes.append((f"1x{n_dev}", make_mesh(1)))

    run_dir = Path(tempfile.mkdtemp(prefix="mesh_scale_"))
    prev_reg = obs.set_registry(obs.Registry())
    prev_sink = obs.configure_sink(
        obs.EventSink(run_dir / "obs" / "events.jsonl"))
    results = []
    try:
        for mesh_label, mesh in meshes:
            batch = 64 * int(mesh.shape["data"]) * 2
            x = jax.random.normal(jax.random.PRNGKey(1), (batch, d))
            for path in ("two_stage", "train_step"):
                members = [
                    FunctionalTiedSAE.init(k, d, n_dict, l1_alpha=1e-3)
                    for k in jax.random.split(jax.random.PRNGKey(0),
                                              n_members)]
                ens = Ensemble(members, FunctionalTiedSAE, mesh=mesh,
                               donate=False, use_fused=True,
                               fused_interpret=not on_tpu,
                               fused_path=path)
                probe = obs.DeviceStepProbe("train", every=1, warmup=0)

                def one(e=ens, xb=x):
                    return e.step_batch(xb)

                one()
                jax.block_until_ready(ens.state.params)
                t0 = time.perf_counter()
                for _ in range(steps):
                    cost = ens.step_cost(batch)
                    # mesh shape folded into the label so the report's
                    # mfu gauges separate per (path, mesh)
                    cost = dataclasses.replace(
                        cost, path=f"{cost.path}@{mesh_label}")
                    probe.measure(one, cost=cost,
                                  block_before=ens.state.params)
                rate = steps * batch / (time.perf_counter() - t0)
                results.append((mesh_label, path, ens.fused_path, rate))
        obs.flush_metrics()
        mfu = build_report(run_dir).get("perf", {}).get("mfu", {})
        for mesh_label, path, resolved, rate in results:
            key = next((k for k in mfu
                        if f"path={resolved}@{mesh_label}" in k), None)
            _emit("mesh_scale", rate, "activations/s",
                  variant=f"{path}@{mesh_label}", resolved_path=resolved,
                  mesh=mesh_label, backend=backend_label,
                  mfu=round(mfu[key], 4) if key is not None else None,
                  **({} if on_tpu
                     else {"note": "interpret-mode kernels on the CPU "
                                   "mesh — ranking evidence only"}))
        # the acceptance ratio on the WIDEST mesh: auto mode must have
        # resolved the whole-step path, and it must not lose to two-stage
        by_key = {(m, p): r for m, p, _, r in results}
        widest = meshes[-1][0]
        ws, ts = by_key[(widest, "train_step")], by_key[(widest,
                                                        "two_stage")]
        _emit("mesh_scale", ws / ts, "ratio",
              variant=f"wholestep_over_twostage@{widest}",
              backend=backend_label)
    finally:
        obs.configure_sink(prev_sink)
        obs.set_registry(prev_reg)
        import shutil

        shutil.rmtree(run_dir, ignore_errors=True)


def bench_seq_parallel(quick: bool) -> None:
    # The pre-r4 version of this suite hung indefinitely behind the axon
    # tunnel (eager shard_map); the jitted _sp_program fixed it, but a
    # regression or wedged tunnel must produce a stack dump and an exit, not
    # a silent ~0%-CPU hang (bench.py's watchdog pattern; ADVICE r4 #3).
    # exit=True is safe to be drastic about because main() runs this suite
    # LAST and every earlier suite's JSON line is already flushed.
    import faulthandler

    faulthandler.dump_traceback_later(600 if quick else 1800, exit=True)
    try:
        _bench_seq_parallel_impl(quick)
    finally:
        faulthandler.cancel_dump_traceback_later()


def _bench_seq_parallel_impl(quick: bool) -> None:
    from sparse_coding_tpu.lm import gptneox
    from sparse_coding_tpu.lm.long_context import sequence_parallel_forward
    from sparse_coding_tpu.lm.model_config import get_config, tiny_test_config
    from sparse_coding_tpu.parallel.mesh import make_mesh

    n_dev = len(jax.devices())
    # n_dev == 1 is a degenerate ring (no ppermute traffic) but still runs
    # the full shard_map + ring-attention program on the chip. The r3 "hang"
    # on this suite was eager shard_map compiling every body op as its own
    # remote program through the tunnel; sequence_parallel_forward now jits
    # the whole program (lm/long_context.py::_sp_program, repro in
    # scripts/repro_seqpar_hang.py).
    mesh = make_mesh(1, n_dev)
    cfg = tiny_test_config("gptneox") if quick else get_config(
        "EleutherAI/pythia-70m-deduped")
    params = gptneox.init_params(jax.random.PRNGKey(0), cfg)
    b, s = (2, 64 * n_dev) if quick else (2, 512 * n_dev)
    toks = jnp.asarray(np.random.default_rng(0).integers(
        0, cfg.vocab_size, (b, s)))

    def one():
        # reduce ON DEVICE: at pythia-70m scale the full logits are ~1.2 GB
        # and returning them ships every byte through the axon tunnel each
        # iteration — the sync would time tunnel bandwidth, not the forward
        logits, _ = sequence_parallel_forward(params, toks, cfg, mesh)
        return jnp.sum(jnp.square(logits))

    rate = _timed(one, 3 if quick else 10, b * s)
    _emit("seq_parallel_forward", rate, "tokens/s", context=s,
          n_shards=n_dev, d_model=cfg.d_model)


def main() -> None:
    parser = argparse.ArgumentParser()
    parser.add_argument("suite", nargs="?", default=None,
                        help="run only this suite (e.g. gateway_soak); "
                             "default runs everything")
    parser.add_argument("--variant", default=None,
                        help="gateway_soak only: static_ladder | "
                             "derived_ladder (default runs both)")
    parser.add_argument("--quick", action="store_true")
    args = parser.parse_args()
    from sparse_coding_tpu.obs import ledger as perf_ledger

    # seq_parallel runs LAST: its hang watchdog exits the process, and every
    # earlier suite's JSON line is flushed by then
    all_suites = (bench_ensemble, bench_ensemble_ratio, bench_big_sae,
                  bench_harvest,
                  bench_chunk_io, bench_ingest_soak, bench_streaming_eval,
                  bench_guardian_soak, bench_perf_probe, bench_gateway,
                  bench_catalog, bench_fleet_soak, bench_group_sae,
                  bench_plane_tide,
                  bench_fsck_scan, bench_mesh_scale, bench_seq_parallel)
    # each suite is addressable by its emitted row name where it
    # differs from the function name (gateway_soak -> bench_gateway)
    by_name = {fn.__name__.removeprefix("bench_"): fn for fn in all_suites}
    by_name["gateway_soak"] = bench_gateway
    if args.suite is not None:
        if args.suite not in by_name:
            raise SystemExit(f"unknown suite {args.suite!r} "
                             f"(choose from {sorted(by_name)})")
        suites = (by_name[args.suite],)
    else:
        suites = all_suites

    rows_before = len(perf_ledger.read_rows())
    for suite in suites:
        try:
            if suite is bench_gateway:
                suite(args.quick, variant=args.variant)
            else:
                suite(args.quick)
        except Exception as e:
            print(f"{suite.__name__} failed: {e!r}", file=sys.stderr)
    # ledger accounting (ISSUE 12): every emitted scenario row must have
    # LANDED in the durable perf ledger — the regression record is only
    # trustworthy if writing it is verified, not assumed
    all_rows = perf_ledger.read_rows()
    landed = len(all_rows) - rows_before
    print(f"perf ledger: {_LEDGER['emitted']} row(s) emitted, "
          f"{_LEDGER['appended']} appended, {landed} landed at "
          f"{perf_ledger.ledger_path()}", file=sys.stderr)
    assert landed >= _LEDGER["emitted"], (
        f"perf ledger lost rows: emitted {_LEDGER['emitted']}, "
        f"landed {landed}")
    # regression exit gate (ROADMAP 3(b), ISSUE 16): this run's suite
    # rows vs the last prior ledger row with the same
    # (suite, variant, unit, backend) — backend in the key means a
    # cpu-fallback round never gates against an on-chip round. A flagged
    # regression exits nonzero so unattended rounds cannot silently rot
    # the record they are supposed to defend. SPARSE_CODING_BENCH_GATE=0
    # disables (exploratory runs); the default 25% bar sits above this
    # serial container's measured host noise (±5-7% per read), override
    # via SPARSE_CODING_BENCH_GATE_THRESHOLD.
    from sparse_coding_tpu.obs.report import (
        diff_ledger_suites,
        format_ledger_diff,
    )

    if os.environ.get("SPARSE_CODING_BENCH_GATE", "1").strip().lower() \
            in ("0", "false", "off"):
        print("bench gate: disabled (SPARSE_CODING_BENCH_GATE)",
              file=sys.stderr)
        return
    threshold = float(os.environ.get(
        "SPARSE_CODING_BENCH_GATE_THRESHOLD", "0.25"))
    diff = diff_ledger_suites(all_rows[:rows_before],
                              all_rows[rows_before:], threshold=threshold)
    print(format_ledger_diff(diff), file=sys.stderr)
    if diff["regressions"]:
        raise SystemExit(3)


if __name__ == "__main__":
    main()
